"""Packed host->device upload engine (ISSUE 10): byte-roundtrip
property tests across every column family, host-pack vs D2H-pack byte
identity, the forced-dd f64 staging formulation, the staging pool
(grow-on-miss / LIFO reuse / LRU trim / leak baseline), structural
1-transfer-per-scan-batch pinning, engine-level on/off equality (incl.
the PR 3 forced-spill unspill lane and the host shuffle read seam),
seeded `device.dispatch` chaos keying with order-independent placement,
the fused split+pack single-dispatch program, the `h2d_upload`
kern_bench family, the `upload` event/metrics surface, and the bench /
profile_report roll-ups."""

import decimal
import json
import os
import sys
from pathlib import Path

import numpy as np
import pytest

from spark_rapids_tpu import config as C
from spark_rapids_tpu import faults
from spark_rapids_tpu.columnar import transfer
from spark_rapids_tpu.columnar import upload
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import Column, host_build
from spark_rapids_tpu.types import (BOOLEAN, BYTE, DOUBLE, FLOAT, INT, LONG,
                                    SHORT, STRING, ArrayType, DecimalType,
                                    MapType, Schema, StructField, StructType)

sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "tools"))
import kern_bench  # noqa: E402

OFF = {"spark.rapids.tpu.transfer.packedUpload.enabled": "false"}


@pytest.fixture(autouse=True)
def _isolation():
    prev = C.active_conf()
    faults.install(None)
    yield
    faults.install(None)
    C.set_active_conf(prev)


def _rich_schema():
    return Schema((
        StructField("b", BOOLEAN), StructField("t", BYTE),
        StructField("h", SHORT), StructField("i", INT),
        StructField("l", LONG), StructField("f", FLOAT),
        StructField("d", DOUBLE), StructField("s", STRING),
        StructField("a", ArrayType(LONG)),
        StructField("m", MapType(LONG, STRING)),
        StructField("st", StructType((StructField("x", LONG),
                                      StructField("y", STRING)))),
        StructField("dec", DecimalType(30, 2)),
    ))


def _rich_data(n, rng):
    def maybe(v, i):
        return None if i % 7 == 3 else v
    return {
        "b": [maybe(bool(x % 2), i)
              for i, x in enumerate(rng.integers(0, 2, n))],
        "t": [maybe(int(x), i)
              for i, x in enumerate(rng.integers(-128, 128, n))],
        "h": [maybe(int(x), i)
              for i, x in enumerate(rng.integers(-3000, 3000, n))],
        "i": [maybe(int(x), i)
              for i, x in enumerate(rng.integers(-10**6, 10**6, n))],
        "l": [maybe(int(x), i)
              for i, x in enumerate(rng.integers(-2**40, 2**40, n))],
        "f": [maybe(float(np.float32(x)), i)
              for i, x in enumerate(rng.random(n))],
        "d": [maybe(float(x), i) for i, x in enumerate(rng.random(n))],
        "s": [maybe(["", "a", "bb", "wörld", "longer-string"][int(x)], i)
              for i, x in enumerate(rng.integers(0, 5, n))],
        "a": [maybe([int(y) for y in rng.integers(0, 9, int(x))], i)
              for i, x in enumerate(rng.integers(0, 4, n))],
        "m": [maybe({int(y): "v" + str(y) for y in rng.integers(0, 5, x)},
                    i)
              for i, x in enumerate(rng.integers(0, 3, n))],
        "st": [maybe({"x": int(x), "y": maybe("s" + str(x), i + 1)}, i)
               for i, x in enumerate(rng.integers(0, 50, n))],
        "dec": [maybe(decimal.Decimal(int(x))
                      .scaleb(-2) * 10**int(abs(x) % 20), i)
                for i, x in enumerate(rng.integers(-10**6, 10**6, n))],
    }


def _leaf_equal(batch_a, batch_b):
    import jax
    la = jax.tree_util.tree_leaves(list(batch_a.columns))
    lb = jax.tree_util.tree_leaves(list(batch_b.columns))
    assert len(la) == len(lb)
    for a, b in zip(la, lb):
        na, nb = np.asarray(a), np.asarray(b)
        assert na.dtype == nb.dtype and na.shape == nb.shape, \
            (na.dtype, nb.dtype, na.shape, nb.shape)
        assert np.array_equal(na, nb, equal_nan=(na.dtype.kind == "f")), \
            na.dtype


# ---------------------------------------------------------------------------
# roundtrip properties
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("n", [0, 5, 64, 200])
def test_packed_roundtrip_every_family(n, rng):
    """Packed upload of host columns is byte-identical (every leaf, incl.
    capacity padding) to the per-buffer device batch they came from."""
    sch = _rich_schema()
    dev = ColumnarBatch.from_pydict(_rich_data(n, rng), sch)
    host_cols, hn = transfer.fetch_batch_host(dev)
    assert hn == n
    up = upload.packed_upload_batch(host_cols, n, sch)
    _leaf_equal(dev, up)
    assert up.num_rows_host == n


def test_host_pack_matches_d2h_pack_bytes(rng):
    """pack_host_batch lays out EXACTLY the D2H wire format: the bytes
    equal np.asarray(_pack_jit(device_batch))."""
    sch = _rich_schema()
    dev = ColumnarBatch.from_pydict(_rich_data(37, rng), sch)
    host_cols, n = transfer.fetch_batch_host(dev)
    expect = np.asarray(transfer._pack_jit(dev))
    buf, total = upload.pack_host_batch(host_cols, n)
    try:
        assert total == expect.shape[0]
        assert (buf[:total] == expect).all()
    finally:
        upload.staging_pool().release(buf)


def test_capacity_padding_roundtrip(rng):
    """Columns grown past their natural bucket (capacity padding)
    roundtrip bit-exact, padding included."""
    with host_build():
        col = Column.from_numpy(
            np.arange(10, dtype=np.int64), LONG,
            validity=np.array([i % 3 != 1 for i in range(10)]),
            capacity=512)
    sch = Schema((StructField("x", LONG),))
    up = upload.packed_upload_batch([col], 10, sch)
    assert np.asarray(up.columns[0].data).shape == (512,)
    assert np.array_equal(np.asarray(up.columns[0].data), col.data)
    assert np.array_equal(np.asarray(up.columns[0].validity), col.validity)


def test_forced_dd_f64_staging(monkeypatch):
    """With the TPU dd-split forced on, f64 uploads stage as (hi, lo)
    float32 pairs and the device reconstructs hi + lo — the exact
    formulation jnp.asarray uses for f64 on a dd-emulating chip."""
    monkeypatch.setattr(transfer, "_dd_split", lambda: True)
    # values whose lo correction is a NORMAL float32 (or zero): XLA CPU
    # flushes subnormal f32 to zero, so a tiny-magnitude double's lo
    # term would legitimately differ from the numpy-computed oracle
    vals = np.array([1.25, 3.141592653589793, 1.0 / 3.0, 1e10 + 0.1,
                     0.0, -0.0, np.nan])
    with host_build():
        col = Column.from_numpy(vals, DOUBLE)
    sch = Schema((StructField("d", DOUBLE),))
    got = np.asarray(upload.packed_upload_batch(
        [col], len(vals), sch).columns[0].data)[: len(vals)]
    hi = vals.astype(np.float32)
    lo = (vals - hi.astype(np.float64)).astype(np.float32)
    expect = hi.astype(np.float64) + lo.astype(np.float64)
    assert np.array_equal(got, expect, equal_nan=True)


def test_upload_leaves_roundtrip(rng):
    """The unspill lane: arbitrary numpy leaf lists (dtypes, 2-D
    shapes) survive the packed leaf upload bit-exact."""
    leaves = [np.arange(10, dtype=np.int64), rng.random((3, 5)),
              np.array([True, False, True]),
              np.arange(4, dtype=np.int16),
              np.arange(6, dtype=np.uint8),
              np.array([], dtype=np.int32)]
    out = upload.upload_leaves(leaves, fault_key="unspill:test")
    assert len(out) == len(leaves)
    for a, b in zip(leaves, out):
        nb = np.asarray(b)
        assert nb.dtype == a.dtype and nb.shape == a.shape
        assert np.array_equal(nb, a)


def test_per_buffer_fallback_unrecognized_tree():
    """A column class the packer does not recognize keeps the
    per-buffer lane (conf on) — the documented nested-type escape
    hatch."""
    class OddColumn(Column):
        pass

    with host_build():
        col = OddColumn(np.arange(4, dtype=np.int64),
                        np.ones(4, np.bool_), LONG)
    before = upload.counters()
    batch = upload.to_device_batch([col], 4, Schema((StructField("x",
                                                                 LONG),)))
    after = upload.counters()
    assert after["per_buffer"] - before["per_buffer"] == 1
    assert after["packed"] - before["packed"] == 0
    assert np.array_equal(np.asarray(batch.columns[0].data),
                          np.arange(4))


# ---------------------------------------------------------------------------
# staging pool
# ---------------------------------------------------------------------------

def test_pool_grow_reuse_and_lru_trim():
    pool = upload.StagingPool()
    b1 = pool.acquire(1000)  # -> 1024 bucket, miss
    assert b1.shape == (1024,) and pool.misses == 1
    pool.release(b1)
    b2 = pool.acquire(900)  # same bucket: LIFO reuse hit
    assert b2 is b1 and pool.hits == 1
    pool.release(b2)
    assert pool.outstanding_bytes() == 0

    C.set_active_conf(C.RapidsConf(
        {"spark.rapids.tpu.transfer.packedUpload.poolBytes": "8k"}))
    pool = upload.StagingPool()
    bufs = [pool.acquire(4096) for _ in range(4)]
    assert pool.outstanding_bytes() == 4 * 4096  # in-flight never capped
    first_released = bufs[0]
    for b in bufs:
        pool.release(b)
    # cap 8k: the two OLDEST-returned buffers were trimmed
    assert pool.pooled_bytes() == 8192 and pool.trims == 2
    got = pool.acquire(4096)
    assert got is not first_released  # LRU victim really left the pool
    pool.release(got)
    assert pool.outstanding_bytes() == 0


def test_pool_presize_from_batch_size(tmp_path):
    """ISSUE 14 satellite (the PR 10 recorded TODO): configure()
    pre-sizes the bucket ladder from batchSizeBytes, so steady-state
    acquires at or under the target are ALL hits — the miss counter
    stays at zero."""
    pool = upload.StagingPool()
    added = pool.presize(64 * 1024, pool_cap=1 << 20)
    assert added == sum(256 << i for i in range(9))  # 256B..64KiB
    # every rung at or under the target acquires as a HIT
    for nbytes in (100, 600, 5000, 40_000, 65_536):
        buf = pool.acquire(nbytes)
        pool.release(buf)
    assert pool.misses == 0 and pool.hits == 5
    # past the target still grows on miss (the pre-ISSUE-14 behavior)
    big = pool.acquire(100_000)
    assert pool.misses == 1
    pool.release(big)
    # idempotent: a second presize with the rungs populated adds nothing
    assert pool.presize(64 * 1024, pool_cap=1 << 20) == 0
    # the cap bounds the ladder: a huge target stops at pool_cap
    capped = upload.StagingPool()
    capped.presize(1 << 30, pool_cap=4096)
    assert capped.pooled_bytes() <= 4096

    # the session-configure seam: a steady-state parquet scan hits the
    # pre-sized ladder with zero grow-on-miss allocations
    import pyarrow as pa
    import pyarrow.parquet as pq
    from spark_rapids_tpu.api.session import TpuSession
    n = 4000
    pq.write_table(pa.table({
        "a": np.arange(n, dtype=np.int64),
        "b": np.arange(n, dtype=np.float64)}),
        tmp_path / "t.parquet")
    upload.reset_staging_pool()
    sess = TpuSession(
        {"spark.rapids.sql.batchSizeBytes": "1m",
         "spark.rapids.tpu.transfer.packedUpload.poolBytes": "16m"})
    proc = upload.staging_pool()
    assert proc.pooled_bytes() > 0 and proc.misses == 0  # pre-sized
    rows = sess.read_parquet(str(tmp_path / "t.parquet")).collect()
    assert len(rows) == n
    proc.settle()
    assert proc.misses == 0, proc.stats()  # zero grow-on-miss uploads
    upload.reset_staging_pool()


def test_concurrent_uploads_never_cross_contaminate():
    """Regression (found live via the PR 6 storm): PJRT CPU zero-copy
    is a PER-BUFFER decision — an aliased staging buffer returned to
    the pool and rewritten by another thread corrupted live device
    arrays. Eight lanes hammer the pool concurrently; every batch must
    read back its own values."""
    import threading
    sch = Schema((StructField("x", LONG), StructField("y", DOUBLE)))

    def mk(v):
        with host_build():
            return [Column.from_numpy(np.full(512, v, np.int64), LONG),
                    Column.from_numpy(np.full(512, float(v)), DOUBLE)]

    errs = []

    def lane(i):
        try:
            for k in range(15):
                v = i * 100 + k
                bt = upload.packed_upload_batch(mk(v), 512, sch)
                x = np.asarray(bt.columns[0].data)[:512]
                y = np.asarray(bt.columns[1].data)[:512]
                assert (x == v).all() and (y == float(v)).all(), \
                    (i, k, x[:3], y[:3])
        except BaseException as e:  # noqa: BLE001 — surfaced below
            errs.append(e)

    threads = [threading.Thread(target=lane, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs, errs[:2]
    upload.staging_pool().settle()
    assert upload.staging_pool().outstanding_bytes() == 0


def test_pool_discard_on_upload_error(monkeypatch):
    """An injected failure during the device copy discards the staging
    buffer (never re-pooled) and leaves no outstanding bytes — the
    conftest tripwire baseline."""
    pool = upload.reset_staging_pool()
    faults.install("device.dispatch:prob=1,seed=1,kind=device,max=1")
    with host_build():
        col = Column.from_numpy(np.arange(8, dtype=np.int64), LONG)
    with pytest.raises(faults.InjectedDeviceError):
        upload.packed_upload_batch([col], 8, Schema(
            (StructField("x", LONG),)), fault_key="k0")
    faults.install(None)
    assert pool.outstanding_bytes() == 0
    assert pool.pooled_bytes() == 0  # discarded, not pooled
    upload.reset_staging_pool()


# ---------------------------------------------------------------------------
# structural transfer pinning + engine equality
# ---------------------------------------------------------------------------

def _write_parquet(tmp_path, rows=600):
    import pyarrow as pa
    import pyarrow.parquet as pq
    rng = np.random.default_rng(11)
    t = pa.table({
        "k": rng.integers(0, 20, rows),
        "v": rng.integers(0, 50, rows),
        "s": [None if i % 9 == 4 else f"s{i % 13}" for i in range(rows)],
    })
    path = os.path.join(str(tmp_path), "data.parquet")
    pq.write_table(t, path)
    return path


def test_scan_batch_pins_one_transfer(tmp_path):
    """Acceptance (ISSUE 10): with packedUpload on (the default) a scan
    batch crosses host->device as exactly ONE transfer; off, it pays
    one per buffer (2-3 per column)."""
    from spark_rapids_tpu.api.session import TpuSession
    path = _write_parquet(tmp_path)

    def drive(settings):
        sess = TpuSession(settings)
        before = upload.counters()
        rows = sess.read_parquet(path).collect()
        after = upload.counters()
        return rows, {k: after[k] - before[k] for k in after}

    rows_on, d_on = drive({})
    assert d_on["uploads"] >= 1 and d_on["packed"] == d_on["uploads"]
    assert d_on["transfers"] == d_on["uploads"]  # ONE per batch
    rows_off, d_off = drive(dict(OFF))
    assert d_off["per_buffer"] == d_off["uploads"] >= 1
    # 3 columns: fixed(2) + fixed(2) + dictionary-coded string(4:
    # codes + validity + dict offsets/bytes — parquet dictionary-encodes
    # strings by default, ISSUE 18) buffers + row count
    assert d_off["transfers"] == 9 * d_off["uploads"]
    assert sorted(rows_on, key=repr) == sorted(rows_off, key=repr)


def _join_agg_query(sess, seed=0):
    from spark_rapids_tpu.api import functions as F
    rng = np.random.default_rng(seed)
    ldata = {"k": [int(x) for x in rng.integers(0, 20, 300)],
             "v": [int(x) for x in rng.integers(0, 50, 300)]}
    rdata = {"k": [int(x) for x in rng.integers(0, 20, 200)],
             "w": [["a", "bb", None, "dddd"][int(x)]
                   for x in rng.integers(0, 4, 200)]}
    lsch = Schema((StructField("k", LONG), StructField("v", LONG)))
    rsch = Schema((StructField("k", LONG), StructField("w", STRING)))
    l = sess.from_pydict(ldata, lsch, batch_rows=64)
    r = sess.from_pydict(rdata, rsch, batch_rows=64)
    return l.join(r, on="k").group_by("k").agg(
        (F.count(), "n")).sort("k")


# moved to the slow tier by ISSUE 13 budget relief (47s: engine-level
# on/off equality; byte-roundtrip property tests + the forced-spill
# equality drive stay tier-1)
@pytest.mark.slow
def test_engine_scan_join_agg_on_off_equality(tmp_path):
    """Engine-level equality: parquet scan -> host-shuffled join ->
    agg -> sort returns identical rows with packedUpload on and off
    (the scan AND shuffle-read seams both ride the packed lane)."""
    from spark_rapids_tpu.api.session import TpuSession
    path = _write_parquet(tmp_path)
    base = {"spark.rapids.sql.shuffle.partitions": "4",
            "spark.rapids.sql.broadcastSizeThreshold": "-1"}

    def drive(settings):
        sess = TpuSession(settings)
        df = sess.read_parquet(path)
        from spark_rapids_tpu.api import functions as F
        joined = df.join(sess.read_parquet(path).select("k"), on="k")
        q = joined.group_by("k").agg((F.count(), "n")).sort("k")
        return q.collect()

    on_rows = drive(base)
    off_rows = drive(dict(base, **OFF))
    assert on_rows == off_rows


def _rows_equal_float_tolerant(xs, ys, float_cols=(1,)):
    """Exact on keys/counts, 1e-9-relative on float sums (the PR 3
    forced-spill tolerance: OOM-retry SPLIT points depend on thread
    interleaving, so float reduction order may differ)."""
    if len(xs) != len(ys):
        return False
    for x, y in zip(xs, ys):
        for i, (a, b) in enumerate(zip(x, y)):
            if i in float_cols:
                if abs(a - b) > 1e-9 * max(abs(a), abs(b), 1.0):
                    return False
            elif a != b:
                return False
    return True


@pytest.mark.slow
def test_forced_spill_unspill_packed_equality(tmp_path):
    """PR 3 forced-spill recipe (the proven scan->filter->join->agg->
    sort parquet shape under a 192 KiB budget): the catalog really
    spills, so unspill restores batches THROUGH the packed leaf lane —
    results identical with packedUpload on and off (float sums to
    reduction-order tolerance). `slow` (nightly): ~16s, and the packed
    unspill lane is unit-covered by test_upload_leaves_roundtrip plus
    every forced-spill suite running under the default-on conf."""
    import pyarrow as pa
    import pyarrow.parquet as pq
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.functions import col, lit
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.memory.budget import reset_memory_budget
    from spark_rapids_tpu.memory.catalog import (buffer_catalog,
                                                 reset_buffer_catalog)
    rng = np.random.default_rng(3)
    n_l, n_o = 4000, 500
    lp = os.path.join(str(tmp_path), "lines.parquet")
    op = os.path.join(str(tmp_path), "orders.parquet")
    pq.write_table(pa.table({
        "l_key": pa.array(rng.integers(0, n_o, n_l), pa.int64()),
        "l_val": pa.array(rng.random(n_l) * 100.0, pa.float64()),
        "l_flag": pa.array(rng.integers(0, 4, n_l), pa.int64()),
    }), lp, row_group_size=512)
    pq.write_table(pa.table({
        "o_key": pa.array(np.arange(n_o), pa.int64()),
        "o_flag": pa.array(rng.integers(0, 10, n_o), pa.int64()),
    }), op, row_group_size=128)

    results, spilled, upload_deltas = {}, {}, {}
    try:
        for mode, settings in (("on", {}), ("off", dict(OFF))):
            reset_buffer_catalog()
            reset_memory_budget(192 * 1024)  # fits one batch, not the query
            settings = dict(settings, **{
                "spark.rapids.memory.spillDirectory": str(tmp_path)})
            sess = TpuSession(settings)
            lines = sess.read_parquet(lp).filter(col("l_flag") != lit(0))
            orders = sess.read_parquet(op).filter(col("o_flag") < lit(5))
            j = lines.join(orders, left_on=["l_key"], right_on=["o_key"])
            agg = j.group_by("l_key").agg((F.sum("l_val"), "rev"),
                                          (F.count(), "cnt"))
            before = upload.counters()
            results[mode] = agg.sort(("rev", False)).collect()
            after = upload.counters()
            spilled[mode] = buffer_catalog().spilled_device_bytes
            upload_deltas[mode] = {k: after[k] - before[k] for k in after}
    finally:
        reset_buffer_catalog()
        reset_memory_budget()
    assert spilled["on"] > 0 and spilled["off"] > 0  # the budget DID bite
    # the packed lane really served the run (scan + unspill seams)
    assert upload_deltas["on"]["packed"] > 0
    assert upload_deltas["on"]["per_buffer"] == 0
    assert upload_deltas["off"]["packed"] == 0
    assert _rows_equal_float_tolerant(results["on"], results["off"])


def test_shuffle_read_decode_stays_host_until_seam(rng):
    """The deserializer returns host-backed batches for the reader
    (device=False) and promotes through the upload engine by default
    — the seam split ISSUE 10 wires."""
    import jax
    from spark_rapids_tpu.shuffle import serializer as ser
    sch = Schema((StructField("k", LONG), StructField("s", STRING)))
    b = ColumnarBatch.from_pydict(
        {"k": [1, None, 3], "s": ["a", None, "cc"]}, sch)
    frame = ser.serialize_batch(b)
    host = ser.deserialize_batch(frame, sch, device=False)
    assert all(isinstance(x, np.ndarray)
               for x in jax.tree_util.tree_leaves(list(host.columns)))
    before = upload.counters()
    dev = ser.deserialize_batch(frame, sch)
    after = upload.counters()
    assert after["transfers"] - before["transfers"] == 1
    assert dev.to_pydict() == b.to_pydict()


# ---------------------------------------------------------------------------
# fused split+pack (round-9 TODO satellite)
# ---------------------------------------------------------------------------

def test_fused_split_pack_frames_byte_identical(rng):
    """The fused split+pack program produces byte-identical shuffle
    frames to the conf-off host partitioner — and unpack_split_host on
    eval_shape templates equals fetch_split_host on real columns."""
    import jax
    import jax.numpy as jnp
    from spark_rapids_tpu.ops.partition_split import (partition_table,
                                                      reorder_columns)
    sch = Schema((StructField("k", LONG), StructField("s", STRING)))
    batch = ColumnarBatch.from_pydict(
        {"k": [int(x) for x in rng.integers(0, 100, 200)],
         "s": [None if x % 5 == 0 else f"v{x}"
               for x in rng.integers(0, 60, 200)]}, sch)
    n_parts = 4
    pid = jnp.asarray(np.asarray(
        rng.integers(0, n_parts, batch.capacity)), jnp.int32)

    def split(b):
        counts, order = partition_table(pid, b.num_rows, b.capacity,
                                        n_parts)
        return counts, reorder_columns(b.columns, order, b.num_rows)

    fused = jax.jit(lambda b: transfer.pack_split(*split(b)))
    tmpl_counts, tmpl_cols = jax.eval_shape(split, batch)
    buf = np.asarray(fused(batch))
    counts_a, cols_a = transfer.unpack_split_host(buf, tmpl_cols, n_parts)
    counts_b, cols_b = transfer.fetch_split_host(*split(batch))
    assert np.array_equal(counts_a, counts_b)
    for a, b in zip(cols_a, cols_b):
        la = jax.tree_util.tree_leaves(a)
        lb = jax.tree_util.tree_leaves(b)
        for x, y in zip(la, lb):
            assert np.array_equal(np.asarray(x), np.asarray(y))


# ---------------------------------------------------------------------------
# chaos: keyed device.dispatch coverage
# ---------------------------------------------------------------------------

def test_upload_chaos_key_placement_order_independent():
    """Seeded injection placement follows the batch's work-item KEY,
    not call order: uploading the same keyed batches in opposite orders
    fires on the same key set (the PR 6 placement-equality pattern)."""
    sch = Schema((StructField("x", LONG),))
    with host_build():
        cols = {f"key-{i:04d}": [Column.from_numpy(
            np.arange(16, dtype=np.int64) + i, LONG)] for i in range(12)}

    def drive(order):
        faults.install("device.dispatch:prob=0.4,seed=7,kind=device")
        hit = set()
        for key in order:
            try:
                upload.packed_upload_batch(cols[key][0:1] and cols[key],
                                           16, sch, fault_key=key)
            except faults.InjectedDeviceError:
                hit.add(key)
        faults.install(None)
        return hit

    keys = sorted(cols)
    a = drive(keys)
    b = drive(list(reversed(keys)))
    assert a == b and a  # same placement, and some draws actually fired


def test_unspill_fault_unwinds_budget_and_quota():
    """A device fault injected into the packed UNSPILL upload (after
    the budget reserve + quota charge, before the tier flip) must
    unwind both — the entry stays HOST, budget.used returns to its
    pre-acquire value, and a retried acquire after disarm succeeds
    (review r1 finding: the leak made every retry double-charge)."""
    from spark_rapids_tpu.columnar.batch import ColumnarBatch as CB
    from spark_rapids_tpu.memory.budget import (memory_budget,
                                                reset_memory_budget)
    from spark_rapids_tpu.memory.catalog import (StorageTier,
                                                 buffer_catalog,
                                                 reset_buffer_catalog)
    from spark_rapids_tpu.memory.spillable import SpillableBatch
    try:
        reset_buffer_catalog()
        reset_memory_budget(1 << 20)
        sch = Schema((StructField("a", LONG),))
        sb = SpillableBatch.from_batch(
            CB.from_pydict({"a": list(range(64))}, sch))
        cat = buffer_catalog()
        assert cat.synchronous_spill(None) > 0
        # async writeback releases the device budget only when the d2h
        # copy LANDS (PR 3) — settle it before snapshotting
        cat.drain_writeback()
        assert cat.tier_of(sb._handle) == StorageTier.HOST
        used_before = memory_budget().used
        faults.install("device.dispatch:prob=1,seed=5,kind=device,max=1")
        with pytest.raises(faults.InjectedDeviceError):
            sb.get_batch()
        faults.install(None)
        assert memory_budget().used == used_before  # reservation unwound
        assert cat.tier_of(sb._handle) == StorageTier.HOST
        got = sb.get_batch()  # clean retry works, charged exactly once
        assert got.to_pydict()["a"][:3] == [0, 1, 2]
        sb.release()
        sb.close()
        assert memory_budget().used == 0
    finally:
        reset_buffer_catalog()
        reset_memory_budget()


def test_upload_fault_recovers_via_task_retry(tmp_path):
    """An injected device fault on the scan upload lane recovers
    through the whole-plan task-retry lane (max=1: the re-execution's
    draws are exhausted) and the query result is correct."""
    from spark_rapids_tpu.api.session import TpuSession
    path = _write_parquet(tmp_path, rows=100)
    sess = TpuSession({
        "spark.rapids.tpu.test.faults":
            "device.dispatch:prob=1,seed=3,kind=device,max=1"})
    rows = sess.read_parquet(path).collect()
    assert len(rows) == 100
    stats = faults.active_plan().stats()
    assert stats.get("device.dispatch") == 1  # it really fired


# ---------------------------------------------------------------------------
# metrics / events / tooling surfaces
# ---------------------------------------------------------------------------

def test_upload_event_and_exec_metrics(monkeypatch, tmp_path):
    """One `upload` event per ingest with lane/seam/transfers;
    numUploads and uploadPackTimeNs register on SourceScanExec."""
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.obs import events
    rows_seen = []
    real = events.emit

    def spy(kind, **fields):
        rows_seen.append({"kind": kind, **fields})
        real(kind, **fields)

    monkeypatch.setattr(events, "emit", spy)
    events.enable(str(tmp_path), "MODERATE")
    try:
        path = _write_parquet(tmp_path)
        sess = TpuSession()
        df = sess.read_parquet(path)
        out = df.collect()
        assert out
        ups = [r for r in rows_seen if r["kind"] == "upload"]
        assert ups and all(u["lane"] == "packed" and u["transfers"] == 1
                           for u in ups)
        assert any(u["seam"] == "scan" for u in ups)
        m = sess.last_query_metrics() or {}
        scan_ups = [v for k, v in m.items() if "numUploads" in str(k)]
        assert scan_ups and sum(scan_ups) >= 1
    finally:
        events.reset_event_bus()


def test_profile_report_uploads_rollup():
    from profile_report import build_report
    events = [
        {"kind": "upload", "lane": "packed", "seam": "scan",
         "bytes": 4096, "rows": 10, "cols": 3, "transfers": 1,
         "pack_ns": 1000},
        {"kind": "upload", "lane": "per_buffer", "seam": "unspill",
         "bytes": 2048, "rows": 0, "cols": 4, "transfers": 4,
         "pack_ns": 500},
    ]
    report = build_report(events)
    assert "uploads: 2 batches (1 packed, 1 per-buffer; 5 h2d" in report


def test_bench_upload_attribution_block():
    import importlib
    sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
    bench = importlib.import_module("bench")
    bench._attr_prev.pop("upload", None)
    first = bench.upload_attribution()
    assert set(first) >= {"uploads", "packed", "per_buffer", "transfers",
                          "bytes", "pack_ns"}
    sch = Schema((StructField("x", LONG),))
    with host_build():
        col = Column.from_numpy(np.arange(8, dtype=np.int64), LONG)
    upload.packed_upload_batch([col], 8, sch)
    delta = bench.upload_attribution()
    assert delta["uploads"] == 1 and delta["packed"] == 1 \
        and delta["transfers"] == 1


def test_kern_bench_h2d_upload_quick(tmp_path):
    """The h2d_upload family runs on CPU via --quick and produces a
    well-formed versioned record (CI smoke, ISSUE 10 satellite)."""
    from spark_rapids_tpu.ops.pallas_tier import KERN_BENCH_SCHEMA
    out = tmp_path / "kb.json"
    kern_bench.main(["--quick", "--families", "h2d_upload",
                     "--out", str(out)])
    doc = json.loads(out.read_text())
    assert doc["schema"] == KERN_BENCH_SCHEMA
    (rec,) = doc["records"]
    assert rec["family"] == "h2d_upload"
    assert rec["winner"] in ("xla", "pallas")
    assert rec["shape"] == [1 << 11, 4]
