"""CSV/JSON Spark-semantics options, ORC scan/write, format writers
(reference GpuCSVScan / GpuJsonReadCommon / GpuOrcScan + writers)."""

import pytest

from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.types import (
    DOUBLE, LONG, STRING, Schema, StructField,
)

SCH = Schema((StructField("a", LONG), StructField("s", STRING)))


def test_csv_options_quote_null_sep(tmp_path):
    p = tmp_path / "t.csv"
    p.write_text('a|s\n1|"x|y"\n2|NA\n3|plain\n')
    sess = TpuSession()
    df = sess.read_csv(str(p), schema=SCH, delimiter="|", null_value="NA")
    assert df.collect() == [(1, "x|y"), (2, None), (3, "plain")]


def test_csv_permissive_skips_malformed(tmp_path):
    p = tmp_path / "bad.csv"
    p.write_text("a,s\n1,x\n2,y,EXTRA,COLS\n3,z\n")
    sess = TpuSession()
    df = sess.read_csv(str(p), schema=SCH)
    src = df._plan.source
    assert df.collect() == [(1, "x"), (3, "z")]
    assert src.malformed_rows == 1
    # FAILFAST surfaces the error
    with pytest.raises(Exception):
        sess.read_csv(str(p), schema=SCH, mode="FAILFAST").collect()


def test_csv_comment_lines(tmp_path):
    p = tmp_path / "c.csv"
    p.write_text("s,a\n#skip me,0\nx,1\n")
    sch = Schema((StructField("s", STRING), StructField("a", LONG)))
    sess = TpuSession()
    df = sess.read_csv(str(p), schema=sch, comment="#")
    assert df.collect() == [("x", 1)]


def test_csv_roundtrip_write(tmp_path):
    sess = TpuSession()
    df = sess.from_pydict({"a": [1, 2, None], "s": ["x", None, "z"]}, SCH)
    p = str(tmp_path / "w.csv")
    df.write_csv(p)
    back = sess.read_csv(p, schema=SCH)
    assert back.collect() == [(1, "x"), (2, None), (None, "z")]


def test_json_permissive_drops_bad_lines(tmp_path):
    p = tmp_path / "t.jsonl"
    p.write_text('{"a": 1, "s": "x"}\nTHIS IS NOT JSON\n{"a": 2, "s": null}\n')
    sess = TpuSession()
    df = sess.read_json(str(p), schema=SCH)
    src = df._plan.source
    assert df.collect() == [(1, "x"), (2, None)]
    assert src.malformed_rows == 1
    with pytest.raises(Exception):
        sess.read_json(str(p), schema=SCH, mode="FAILFAST").collect()


def test_json_roundtrip_write(tmp_path):
    sess = TpuSession()
    df = sess.from_pydict({"a": [1, None], "s": ["x", "y"]}, SCH)
    p = str(tmp_path / "w.jsonl")
    df.write_json(p)
    back = sess.read_json(p, schema=SCH)
    assert back.collect() == [(1, "x"), (None, "y")]


def test_orc_roundtrip(tmp_path):
    sess = TpuSession()
    sch = Schema((StructField("a", LONG), StructField("s", STRING),
                  StructField("d", DOUBLE)))
    data = {"a": [1, 2, None, 4], "s": ["x", None, "zz", ""],
            "d": [1.5, -2.0, 0.0, None]}
    df = sess.from_pydict(data, sch)
    p = str(tmp_path / "t.orc")
    df.write_orc(p)
    back = sess.read_orc(p)
    assert back.collect() == list(zip(data["a"], data["s"], data["d"]))
    # column pruning
    pruned = sess.read_orc(p, columns=["s"])
    assert pruned.collect() == [(v,) for v in data["s"]]


def test_avro_roundtrip(tmp_path):
    """Self-contained avro container reader/writer (reference
    GpuAvroScan.scala + AvroDataFileReader.scala): deflate codec,
    nullable unions, date/timestamp logical types."""
    from spark_rapids_tpu.io.avro import write_avro
    from spark_rapids_tpu.types import (DATE, DOUBLE, LONG, STRING,
                                        TIMESTAMP, Schema, StructField)
    sch = Schema((StructField("l", LONG), StructField("d", DOUBLE),
                  StructField("s", STRING), StructField("dt", DATE),
                  StructField("ts", TIMESTAMP)))
    data = {
        "l": [1, None, -(1 << 40), 7],
        "d": [1.5, float("inf"), None, -0.0],
        "s": ["a", None, "värde", ""],
        "dt": [0, 19000, None, -141427],
        "ts": [0, None, 1_700_000_000_000_000, -1],
    }
    sess = TpuSession()
    df = sess.from_pydict(data, sch)
    path = str(tmp_path / "t.avro")
    write_avro(df, path)
    got = sess.read_avro(path).collect()
    assert got == df.collect()
    # column pruning
    assert sess.read_avro(path, columns=["s", "l"]).collect() == \
        [(s, l) for l, s in zip(data["l"], data["s"])]


def test_avro_reader_against_hand_built_spec_bytes(tmp_path):
    """Reader cross-check against a file whose bytes are written out
    LITERALLY from the Avro 1.11 spec (no shared encoder), so a
    symmetric encode/decode bug in this module cannot hide."""
    import json as _json

    schema = {"type": "record", "name": "r", "fields": [
        {"name": "i", "type": ["null", "int"]},
        {"name": "s", "type": "string"},
    ]}
    schema_b = _json.dumps(schema).encode()
    sync = bytes(range(16))

    def zz(v):  # zigzag varint, written independently from the spec
        u = (v << 1) ^ (v >> 63) if v < 0 else v << 1
        out = b""
        while True:
            if u < 0x80:
                return out + bytes([u])
            out += bytes([(u & 0x7F) | 0x80])
            u >>= 7

    header = (b"Obj\x01"
              + zz(2)                                   # 2 meta entries
              + zz(len(b"avro.schema")) + b"avro.schema"
              + zz(len(schema_b)) + schema_b
              + zz(len(b"avro.codec")) + b"avro.codec"
              + zz(len(b"null")) + b"null"
              + zz(0)                                    # end of map
              + sync)
    # rows: (7, "hi"), (None, "x"), (-3, "")
    body = (zz(1) + zz(7) + zz(2) + b"hi"
            + zz(0) + zz(1) + b"x"
            + zz(1) + zz(-3) + zz(0))
    block = zz(3) + zz(len(body)) + body + sync
    path = str(tmp_path / "spec.avro")
    with open(path, "wb") as f:
        f.write(header + block)

    sess = TpuSession()
    assert sess.read_avro(path).collect() == \
        [(7, "hi"), (None, "x"), (-3, "")]


def test_avro_schema_mismatch_across_files_rejected(tmp_path):
    from spark_rapids_tpu.io.avro import write_avro
    from spark_rapids_tpu.types import INT, LONG, Schema, StructField
    sess = TpuSession()
    d1 = sess.from_pydict({"i": [1]}, Schema((StructField("i", INT),)))
    d2 = sess.from_pydict({"j": [2]}, Schema((StructField("j", LONG),)))
    write_avro(d1, str(tmp_path / "a.avro"))
    write_avro(d2, str(tmp_path / "b.avro"))
    with pytest.raises(ValueError, match="schema mismatch"):
        sess.read_avro(str(tmp_path)).collect()
