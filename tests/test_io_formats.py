"""CSV/JSON Spark-semantics options, ORC scan/write, format writers
(reference GpuCSVScan / GpuJsonReadCommon / GpuOrcScan + writers)."""

import pytest

from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.types import (
    DOUBLE, LONG, STRING, Schema, StructField,
)

SCH = Schema((StructField("a", LONG), StructField("s", STRING)))


def test_csv_options_quote_null_sep(tmp_path):
    p = tmp_path / "t.csv"
    p.write_text('a|s\n1|"x|y"\n2|NA\n3|plain\n')
    sess = TpuSession()
    df = sess.read_csv(str(p), schema=SCH, delimiter="|", null_value="NA")
    assert df.collect() == [(1, "x|y"), (2, None), (3, "plain")]


def test_csv_permissive_skips_malformed(tmp_path):
    p = tmp_path / "bad.csv"
    p.write_text("a,s\n1,x\n2,y,EXTRA,COLS\n3,z\n")
    sess = TpuSession()
    df = sess.read_csv(str(p), schema=SCH)
    src = df._plan.source
    assert df.collect() == [(1, "x"), (3, "z")]
    assert src.malformed_rows == 1
    # FAILFAST surfaces the error
    with pytest.raises(Exception):
        sess.read_csv(str(p), schema=SCH, mode="FAILFAST").collect()


def test_csv_comment_lines(tmp_path):
    p = tmp_path / "c.csv"
    p.write_text("s,a\n#skip me,0\nx,1\n")
    sch = Schema((StructField("s", STRING), StructField("a", LONG)))
    sess = TpuSession()
    df = sess.read_csv(str(p), schema=sch, comment="#")
    assert df.collect() == [("x", 1)]


def test_csv_roundtrip_write(tmp_path):
    sess = TpuSession()
    df = sess.from_pydict({"a": [1, 2, None], "s": ["x", None, "z"]}, SCH)
    p = str(tmp_path / "w.csv")
    df.write_csv(p)
    back = sess.read_csv(p, schema=SCH)
    assert back.collect() == [(1, "x"), (2, None), (None, "z")]


def test_json_permissive_drops_bad_lines(tmp_path):
    p = tmp_path / "t.jsonl"
    p.write_text('{"a": 1, "s": "x"}\nTHIS IS NOT JSON\n{"a": 2, "s": null}\n')
    sess = TpuSession()
    df = sess.read_json(str(p), schema=SCH)
    src = df._plan.source
    assert df.collect() == [(1, "x"), (2, None)]
    assert src.malformed_rows == 1
    with pytest.raises(Exception):
        sess.read_json(str(p), schema=SCH, mode="FAILFAST").collect()


def test_json_roundtrip_write(tmp_path):
    sess = TpuSession()
    df = sess.from_pydict({"a": [1, None], "s": ["x", "y"]}, SCH)
    p = str(tmp_path / "w.jsonl")
    df.write_json(p)
    back = sess.read_json(p, schema=SCH)
    assert back.collect() == [(1, "x"), (None, "y")]


def test_orc_roundtrip(tmp_path):
    sess = TpuSession()
    sch = Schema((StructField("a", LONG), StructField("s", STRING),
                  StructField("d", DOUBLE)))
    data = {"a": [1, 2, None, 4], "s": ["x", None, "zz", ""],
            "d": [1.5, -2.0, 0.0, None]}
    df = sess.from_pydict(data, sch)
    p = str(tmp_path / "t.orc")
    df.write_orc(p)
    back = sess.read_orc(p)
    assert back.collect() == list(zip(data["a"], data["s"], data["d"]))
    # column pruning
    pruned = sess.read_orc(p, columns=["s"])
    assert pruned.collect() == [(v,) for v in data["s"]]


def test_avro_gated():
    sess = TpuSession()
    with pytest.raises(ImportError):
        sess.read_avro("/nonexistent.avro")
