"""Interpret-mode property tests for the DMA gather kernel family and
the gather engine (ISSUE 8): the packed row gather must match the XLA
formulation bit-for-bit on randomized inputs — null masks, mixed column
widths, capacity-bucket padding, out-of-range and empty index sets —
and the engine must produce byte-identical results with the gather tier
on or off. The gather-count drop is asserted STRUCTURALLY (counts, not
timing) via the numGathers metric and the gather_stats event log.
"""

import glob
import json
import sys
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from spark_rapids_tpu.columnar.column import Column, bucket_capacity
from spark_rapids_tpu.ops import gather as G
from spark_rapids_tpu.ops.pallas_gather import pallas_gather_rows
from spark_rapids_tpu.ops.rowpack import gather_rows, pack_rows
from spark_rapids_tpu.types import (
    BOOLEAN, BYTE, DOUBLE, FLOAT, INT, LONG, SHORT, Schema, StructField,
)

sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "tools"))
import kern_bench  # noqa: E402


def _col(np_arr, dtype, null_every=0, capacity=None):
    c = Column.from_numpy(np_arr, dtype,
                          capacity=capacity or bucket_capacity(len(np_arr)))
    if null_every:
        v = np.asarray(c.validity).copy()
        v[::null_every] = False
        c = Column(c.data, jnp.asarray(v), dtype)
    return c


def _mixed_cols(rng, n, null_every=5):
    """One column of every packable width class (bool, i8, i16, i32,
    i64, f32, f64), nulls sprinkled at different cadences."""
    return [
        _col(rng.integers(0, 2, n).astype(bool), BOOLEAN, null_every),
        _col(rng.integers(-100, 100, n).astype(np.int8), BYTE, 0),
        _col(rng.integers(-1000, 1000, n).astype(np.int16), SHORT,
             max(0, null_every - 2)),
        _col(rng.integers(-(2**28), 2**28, n).astype(np.int32), INT, 3),
        _col(rng.integers(-(2**60), 2**60, n).astype(np.int64), LONG,
             null_every),
        _col(rng.random(n).astype(np.float32), FLOAT, 0),
        _col(rng.random(n) * 1e6, DOUBLE, 7),
    ]


def _assert_pair_equal(xla, pal):
    gi_x, gf_x = xla
    gi_p, gf_p = pal
    assert np.array_equal(np.asarray(gi_x), np.asarray(gi_p))
    assert (gf_x is None) == (gf_p is None)
    if gf_x is not None:
        # bit-level: the kernel moves f64 as u32 lane pairs
        assert np.array_equal(
            np.asarray(gf_x).view(np.uint64),
            np.asarray(gf_p).view(np.uint64))


@pytest.mark.parametrize("seed,n,n_out,oob", [
    (0, 700, 1500, True),    # duplicates + out-of-range + -1 padding
    (1, 64, 64, False),      # oob-free permutation-ish set
    (2, 1, 300, True),       # single-row source
])
def test_dma_gather_matches_xla_mixed_widths(seed, n, n_out, oob):
    rng = np.random.default_rng(seed)
    cols = _mixed_cols(rng, n)
    plan, imat, fmat = pack_rows(cols)
    cap = cols[0].capacity
    lo = -5 if oob else 0
    hi = cap + 7 if oob else n
    idx_np = rng.integers(lo, hi, n_out).astype(np.int32)
    if oob:
        idx_np[:: max(1, n_out // 9)] = -1  # capacity-padding slots
    idx = jnp.asarray(idx_np)
    _assert_pair_equal(gather_rows(plan, imat, fmat, idx),
                       pallas_gather_rows(plan, imat, fmat, idx,
                                          interpret=True))


def test_dma_gather_int_only_no_f64_matrix():
    """No f64 columns -> fmat is None end to end."""
    rng = np.random.default_rng(3)
    cols = [_col(rng.integers(0, 99, 500).astype(np.int64), LONG, 4),
            _col(rng.integers(0, 9, 500).astype(np.int32), INT, 0)]
    plan, imat, fmat = pack_rows(cols)
    assert fmat is None
    idx = jnp.asarray(rng.integers(-3, 600, 800).astype(np.int32))
    _assert_pair_equal(gather_rows(plan, imat, fmat, idx),
                       pallas_gather_rows(plan, imat, fmat, idx,
                                          interpret=True))


def test_dma_gather_all_invalid_index_set():
    """Every index out of range -> all-invalid rows, like the XLA path."""
    rng = np.random.default_rng(4)
    cols = _mixed_cols(rng, 128, null_every=0)
    plan, imat, fmat = pack_rows(cols)
    idx = jnp.full((256,), -1, jnp.int32)
    gi_p, gf_p = pallas_gather_rows(plan, imat, fmat, idx, interpret=True)
    _assert_pair_equal(gather_rows(plan, imat, fmat, idx), (gi_p, gf_p))
    nv = plan.n_valid_lanes
    assert not np.asarray(gi_p[:, :nv]).any()  # validity lanes zeroed


def test_gather_batch_columns_matches_per_column():
    """The engine helper's packed path == per-column gather_column for
    every width class, including the masked tail."""
    from spark_rapids_tpu.ops.basic import active_mask, gather_column
    rng = np.random.default_rng(5)
    n = 400
    cols = _mixed_cols(rng, n)
    idx = jnp.asarray(rng.integers(0, n, 512).astype(np.int32))
    n_rows = jnp.int32(300)
    out = G.gather_batch_columns(cols, idx, num_rows=n_rows)
    midx = jnp.where(active_mask(n_rows, 512), idx, -1)
    for got, c in zip(out, cols):
        ref = gather_column(c, midx)
        assert np.array_equal(np.asarray(got.validity),
                              np.asarray(ref.validity))
        assert np.array_equal(
            np.asarray(got.data).view(np.uint8).tobytes(),
            np.asarray(ref.data).view(np.uint8).tobytes())


# --- measured-tier selection -------------------------------------------


def _tier_conf(path, mode="auto"):
    from spark_rapids_tpu.config import RapidsConf, set_active_conf
    set_active_conf(RapidsConf({
        "spark.rapids.tpu.pallas.fusedTier": mode,
        "spark.rapids.tpu.pallas.fusedTier.benchFile": str(path)}))


def _gather_record(shape, win=True):
    from spark_rapids_tpu.ops.pallas_tier import (
        KERN_BENCH_SCHEMA, shape_bucket)
    return {"schema": KERN_BENCH_SCHEMA, "family": "gather",
            "platform": jax.default_backend(),
            "shape_bucket": list(shape_bucket(shape)),
            "xla_ms": 10.0 if win else 1.0,
            "pallas_ms": 2.0 if win else 5.0}


def test_gather_tier_requires_a_measurement(tmp_path):
    from spark_rapids_tpu.config import RapidsConf, set_active_conf
    from spark_rapids_tpu.ops.pallas_tier import (
        KERN_BENCH_SCHEMA, fused_tier_enabled)
    try:
        _tier_conf(tmp_path / "none.json")
        assert not fused_tier_enabled("gather", (1024, 512))
        p = tmp_path / "kb.json"
        p.write_text(json.dumps({
            "schema": KERN_BENCH_SCHEMA,
            "records": [_gather_record((1024, 512))]}))
        _tier_conf(p)
        assert fused_tier_enabled("gather", (1024, 512))
        assert not fused_tier_enabled("gather", (4096, 512))  # other bucket
        assert not fused_tier_enabled("join_probe", (1024, 512))
    finally:
        set_active_conf(RapidsConf())


def test_stale_schema_bench_file_is_ignored_loudly(tmp_path):
    """A kern_bench.json from an older layout (missing/mismatched
    schema stamp) must not flip tiers — and must say so, not silently
    degrade (ISSUE 8 satellite)."""
    from spark_rapids_tpu.config import RapidsConf, set_active_conf
    from spark_rapids_tpu.ops.pallas_tier import fused_tier_enabled
    try:
        p = tmp_path / "stale.json"
        rec = _gather_record((1024, 512))
        del rec["schema"]
        p.write_text(json.dumps({"records": [rec]}))  # no doc stamp
        _tier_conf(p)
        with pytest.warns(UserWarning, match="ignoring kern_bench"):
            assert not fused_tier_enabled("gather", (1024, 512))
    finally:
        set_active_conf(RapidsConf())


def test_kern_bench_quick_record_consulted_by_tier(tmp_path):
    """Acceptance: `kern_bench --quick` produces a well-formed
    versioned record that pallas_tier reads (and auto still keeps the
    XLA floor on CPU, where the interpreter loses by construction)."""
    from spark_rapids_tpu.config import RapidsConf, set_active_conf
    from spark_rapids_tpu.ops.pallas_tier import (
        KERN_BENCH_SCHEMA, bench_record)
    out = tmp_path / "kb.json"
    kern_bench.main(["--quick", "--families", "gather",
                     "--out", str(out)])
    doc = json.loads(out.read_text())
    assert doc["schema"] == KERN_BENCH_SCHEMA
    (rec,) = doc["records"]
    assert rec["family"] == "gather" and rec["schema"] == KERN_BENCH_SCHEMA
    assert rec["winner"] in ("xla", "pallas")
    try:
        _tier_conf(out)
        got = bench_record("gather", tuple(rec["shape"]))
        assert got is not None and got["xla_ms"] == rec["xla_ms"]
    finally:
        set_active_conf(RapidsConf())


# --- engine-level equality + structural gather counts ------------------


def _q3_join_session(extra_conf=None):
    """q3-shaped join + aggregate: orders (build) x lineitem (stream),
    fixed-width payload on both sides."""
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.expr.aggexprs import Sum
    from spark_rapids_tpu.expr.core import col, lit
    conf = {"spark.rapids.sql.batchSizeBytes": 16 << 10}
    conf.update(extra_conf or {})
    sess = TpuSession(conf)
    rng = np.random.default_rng(17)
    no, nl = 300, 1200
    o_schema = Schema((StructField("o_key", LONG),
                       StructField("o_flag", INT)))
    l_schema = Schema((StructField("l_key", LONG),
                       StructField("l_price", DOUBLE),
                       StructField("l_qty", LONG)))
    df_o = sess.from_pydict(
        {"o_key": np.arange(no, dtype=np.int64).tolist(),
         "o_flag": rng.integers(0, 10, no).tolist()}, o_schema)
    df_l = sess.from_pydict(
        {"l_key": rng.integers(0, no, nl).tolist(),
         "l_price": (rng.random(nl) * 1000).round(6).tolist(),
         "l_qty": rng.integers(1, 50, nl).tolist()}, l_schema)
    q = (df_l.join(df_o, left_on="l_key", right_on="o_key", how="inner")
             .filter(col("o_flag") < lit(8))
             .group_by("o_flag")
             .agg((Sum(col("l_price")), "rev"), (Sum(col("l_qty")), "q")))
    return sess, q


def _collect_sorted(q):
    return sorted(map(tuple, q.collect()))


def test_gather_tier_engine_equality_q3_join(tmp_path):
    """auto + a recorded gather win (EVERY bucket, so all shapes route
    through the DMA kernel) must be byte-identical to the tier off —
    and the kernel must actually have run."""
    from spark_rapids_tpu.config import RapidsConf, set_active_conf
    from spark_rapids_tpu.ops.pallas_gather import kernel_trace_count
    from spark_rapids_tpu.ops.pallas_tier import KERN_BENCH_SCHEMA
    recs = [_gather_record((1 << i, 1 << j))
            for i in range(4, 22) for j in range(4, 22)]
    p = tmp_path / "kb.json"
    p.write_text(json.dumps({"schema": KERN_BENCH_SCHEMA,
                             "records": recs}))
    try:
        _sess, q_off = _q3_join_session(
            {"spark.rapids.tpu.pallas.fusedTier": "off"})
        off = _collect_sorted(q_off)
        before = kernel_trace_count()
        _sess2, q_on = _q3_join_session({
            "spark.rapids.tpu.pallas.fusedTier": "auto",
            "spark.rapids.tpu.pallas.fusedTier.benchFile": str(p)})
        on = _collect_sorted(q_on)
        assert kernel_trace_count() > before  # the DMA kernel engaged
        assert off == on
    finally:
        set_active_conf(RapidsConf())


def test_gather_tier_engine_equality_filter_heavy(tmp_path):
    """Filter-heavy plan (compaction path, ops/basic.compact_columns):
    byte-identical with the gather tier on vs off."""
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.config import RapidsConf, set_active_conf
    from spark_rapids_tpu.expr.core import col, lit
    from spark_rapids_tpu.ops.pallas_tier import KERN_BENCH_SCHEMA

    def drive(conf):
        sess = TpuSession(conf)
        rng = np.random.default_rng(23)
        n = 3000
        schema = Schema((StructField("a", LONG), StructField("b", INT),
                         StructField("c", DOUBLE),
                         StructField("d", BOOLEAN)))
        df = sess.from_pydict(
            {"a": rng.integers(0, 1000, n).tolist(),
             "b": rng.integers(-50, 50, n).tolist(),
             "c": (rng.random(n) * 100).tolist(),
             "d": rng.integers(0, 2, n).astype(bool).tolist()}, schema)
        q = (df.filter(col("a") % lit(3) == lit(0))
               .filter(col("b") > lit(-25))
               .filter(col("d") == lit(True)))
        return sorted(map(tuple, q.collect()))

    recs = [_gather_record((1 << i, 1 << j))
            for i in range(4, 22) for j in range(4, 22)]
    p = tmp_path / "kb.json"
    p.write_text(json.dumps({"schema": KERN_BENCH_SCHEMA,
                             "records": recs}))
    try:
        off = drive({"spark.rapids.tpu.pallas.fusedTier": "off"})
        on = drive({
            "spark.rapids.tpu.pallas.fusedTier": "auto",
            "spark.rapids.tpu.pallas.fusedTier.benchFile": str(p)})
        assert off == on and len(off) > 0
    finally:
        set_active_conf(RapidsConf())


def test_structural_gather_count_per_join_iteration(tmp_path):
    """The gather-elimination acceptance: with the tier on, the join
    probe materializes <= 3 row gathers PER STREAM ITERATION (one index
    materialization + one packed payload gather per side — down from
    the ~10 per-column payload gathers docs/perf.md r5 measured), and
    the numGathers totals reconcile with the gather_stats event and the
    op_close span batches. Counts only — CPU-runnable."""
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.config import RapidsConf, set_active_conf
    from spark_rapids_tpu.obs import events
    try:
        sess, q = _q3_join_session({
            "spark.rapids.tpu.pallas.fusedTier": "on",
            # ISSUE 14: this test pins the PER-OP join exec's
            # structural gather discipline (the fused stage reuses the
            # same probe kernel; its gather accounting is covered by
            # test_stage_compiler)
            "spark.rapids.tpu.stage.fusion.enabled": "false",
            "spark.rapids.tpu.eventLog.enabled": True,
            "spark.rapids.tpu.eventLog.dir": str(tmp_path)})
        rows = q.collect()
        assert rows
        logged = []
        for f in glob.glob(str(tmp_path / "events-*.jsonl")):
            with open(f) as fh:
                logged += [json.loads(ln) for ln in fh if ln.strip()]
        gs = [e for e in logged if e.get("kind") == "gather_stats"
              and "HashJoin" in (e.get("op") or "")]
        assert gs, "join emitted no gather_stats event"
        closes = {e.get("op_id"): e for e in logged
                  if e.get("kind") == "op_close"}
        for e in gs:
            oc = closes.get(e.get("op_id"))
            assert oc is not None and oc["batches"] >= 1
            per_iter = e["count"] / oc["batches"]
            assert per_iter <= 3, (e, oc)
            assert e["packed"] >= 2 * oc["batches"]  # both sides packed
    finally:
        events.reset_event_bus()
        set_active_conf(RapidsConf())
        TpuSessionReset()


def TpuSessionReset():
    from spark_rapids_tpu.api.session import TpuSession
    TpuSession()


def test_filter_numgathers_metric_counts_one_packed_gather():
    """FilterExec's compaction = ONE packed row gather per batch for an
    all-fixed-width schema (the engine-wide helper at work)."""
    from spark_rapids_tpu.exec.basic import FilterExec, InMemoryScanExec
    from spark_rapids_tpu.expr.core import col, lit
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    rng = np.random.default_rng(31)
    n = 500
    schema = Schema((StructField("a", LONG), StructField("b", DOUBLE)))
    cols = [_col(rng.integers(0, 50, n).astype(np.int64), LONG),
            _col(rng.random(n) * 10, DOUBLE)]
    batches = [ColumnarBatch(cols, n, schema)] * 3
    f = FilterExec((col("a") > lit(10)), InMemoryScanExec(batches, schema))
    out = list(f.execute())
    assert len(out) == 3
    assert f.metrics["numGathers"].value == 3  # one packed gather each
    assert f.metrics["gatherTimeNs"].value > 0
