"""percentile / approx_percentile aggregates (exact computation)."""
import random

import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.types import LONG, STRING, Schema, StructField


def _df(sess, keys, vals):
    return sess.from_pydict(
        {"k": keys, "v": vals},
        schema=Schema((StructField("k", STRING), StructField("v", LONG))))


def test_percentile_group_by():
    sess = TpuSession()
    keys = ["a"] * 10 + ["b"] * 5 + ["c"]
    vals = list(range(1, 11)) + [10, 20, 30, 40, 50] + [7]
    out = sorted(_df(sess, keys, vals).group_by("k").agg(
        (F.percentile(F.col("v"), 0.5), "p50"),
        (F.approx_percentile(F.col("v"), 0.5), "ap50"),
        (F.percentile(F.col("v"), [0.0, 0.5, 1.0]), "pm")).collect())
    assert out[0] == ("a", 5.5, 5, [1.0, 5.5, 10.0])
    assert out[1] == ("b", 30.0, 30, [10.0, 30.0, 50.0])
    assert out[2] == ("c", 7.0, 7, [7.0, 7.0, 7.0])


def test_percentile_nulls():
    sess = TpuSession()
    out = sorted(_df(sess, ["x", "x", "y"], [None, 4, None])
                 .group_by("k")
                 .agg((F.percentile(F.col("v"), 0.5), "p")).collect())
    assert out == [("x", 4.0), ("y", None)]


def test_grand_approx_percentile():
    sess = TpuSession()
    vals = [9, 1, 7, 3, 5, 8, 2, 6, 4, 10]
    out = _df(sess, ["g"] * 10, vals).agg(
        (F.approx_percentile(F.col("v"), 0.25), "q1")).collect()
    assert out == [(sorted(vals)[2],)]  # ceil(0.25*10)-1 = index 2


@pytest.mark.slow  # ~6s; fuzz sweep nightly like the PR 1 moves (round-7 budget move)
def test_percentile_fuzz_vs_oracle():
    rng = random.Random(11)
    sess = TpuSession()
    keys = [rng.choice("pqr") for _ in range(120)]
    vals = [None if rng.random() < 0.15 else rng.randint(-50, 50)
            for _ in range(120)]
    out = dict((r[0], (r[1], r[2])) for r in
               _df(sess, keys, vals).group_by("k").agg(
                   (F.percentile(F.col("v"), 0.3), "p"),
                   (F.approx_percentile(F.col("v"), 0.3), "ap"))
               .collect())
    import math
    for k in "pqr":
        xs = sorted(v for kk, v in zip(keys, vals)
                    if kk == k and v is not None)
        if not xs:
            assert out[k] == (None, None)
            continue
        rank = 0.3 * (len(xs) - 1)
        lo, hi = math.floor(rank), math.ceil(rank)
        interp = xs[lo] + (rank - lo) * (xs[hi] - xs[lo])
        nearest = xs[max(0, math.ceil(0.3 * len(xs)) - 1)]
        assert abs(out[k][0] - interp) < 1e-9, k
        assert out[k][1] == nearest, k


def test_multi_percentage_all_null_group_is_null():
    sess = TpuSession()
    out = sorted(_df(sess, ["x", "y"], [None, 3]).group_by("k").agg(
        (F.percentile(F.col("v"), [0.25, 0.75]), "p")).collect())
    assert out == [("x", None), ("y", [3.0, 3.0])]
