"""Docs/registry drift lint (ISSUE 2 satellite): every conf key the
code uses resolves to the registry and is documented in docs/configs.md
(unless internal), and every additional_metrics() name is canonical and
unique — one name, one meaning, across the exec tree (reference
GpuMetric companion discipline).

ISSUE 12: the AST scanning (source discovery, conf-key literal walk,
unregistered-key and unregistered-event-kind detection) lives in
`spark_rapids_tpu.analysis` now — ONE rule registry. This file keeps
only the doc-TABLE assertions the analyzer doesn't own (a markdown
table matching a Python registry) and delegates every code walk to
`analysis.scan` / the `registry-drift` rules."""

import importlib
import re
import sys
from pathlib import Path

import pytest

from spark_rapids_tpu import analysis
from spark_rapids_tpu import config as cfg
from spark_rapids_tpu.exec import base as exec_base

ROOT = Path(__file__).resolve().parents[1]


def test_conf_keys_in_code_are_registered_and_documented():
    """ONE walk: source discovery and the conf-key literal scan are the
    analyzer's (`analysis.scan` — the same scanner the
    `conf-key-registered` rule runs on, which the contract-check tier-1
    gate enforces with suppression/baseline support package-wide); this
    test derives both halves — registration and docs presence — from
    that single pass."""
    docs = (ROOT / "docs" / "configs.md").read_text()
    dynamic = cfg.RapidsConf._DYNAMIC_PREFIXES
    problems = []
    for path in analysis.default_source_files(ROOT):
        for key, lineno in analysis.conf_key_literals(path):
            where = f"{path.relative_to(ROOT)}:{lineno}"
            entry = cfg._REGISTRY.get(key)
            if entry is None:
                if not key.startswith(dynamic):
                    problems.append(f"{where}: {key} not in the config "
                                    "registry")
            elif not entry.internal and f"`{key}`" not in docs:
                problems.append(f"{where}: {key} missing from "
                                "docs/configs.md — run tools/gen_docs.py")
    assert not problems, "\n".join(problems)


def test_registry_docs_are_current():
    """docs/configs.md is exactly what generate_docs() renders — a
    stale file fails here, not in review."""
    assert (ROOT / "docs" / "configs.md").read_text() \
        == cfg.generate_docs(), "run tools/gen_docs.py"


def _all_exec_classes():
    pkg_dir = ROOT / "spark_rapids_tpu" / "exec"
    for py in sorted(pkg_dir.glob("*.py")):
        importlib.import_module(f"spark_rapids_tpu.exec.{py.stem}")

    def subclasses(cls):
        for c in cls.__subclasses__():
            yield c
            yield from subclasses(c)

    return sorted(set(subclasses(exec_base.TpuExec)),
                  key=lambda c: c.__name__)


def test_fault_point_registry_matches_docs():
    """docs/robustness.md's fault-point table lists exactly the points
    registered in faults.FAULT_POINTS (ISSUE 4: the same drift lint the
    conf registry gets) — and every registered point appears at its
    real call site somewhere in the package."""
    from spark_rapids_tpu import faults
    docs = (ROOT / "docs" / "robustness.md").read_text()
    documented = set(re.findall(r"^\|\s*`([a-z_]+\.[a-z_0-9]+)`\s*\|",
                                docs, re.MULTILINE))
    registered = set(faults.FAULT_POINTS)
    assert documented == registered, (
        f"docs/robustness.md fault table drifted: "
        f"missing={sorted(registered - documented)} "
        f"stale={sorted(documented - registered)}")
    # every point is wired: its name appears as a literal in a real
    # call site (outside faults.py itself)
    src = "".join(p.read_text()
                  for p in (ROOT / "spark_rapids_tpu").rglob("*.py")
                  if p.name != "faults.py")
    unwired = [p for p in registered if f'"{p}"' not in src]
    assert not unwired, f"registered fault points with no call site: {unwired}"


def test_breaker_tables_match_registry():
    """docs/robustness.md's circuit-breaker domain and state tables
    list exactly lifecycle.BREAKER_DOMAINS / BREAKER_STATES (ISSUE 6:
    the same drift lint the fault-point table gets). The check is
    scoped to the breaker section so taxonomy/fault tables elsewhere in
    the doc can't collide."""
    from spark_rapids_tpu.exec import lifecycle
    docs = (ROOT / "docs" / "robustness.md").read_text()
    m = re.search(r"## Degradation circuit breakers\n(.*?)(?:\n## |\Z)",
                  docs, re.DOTALL)
    assert m, "docs/robustness.md lost its circuit-breaker section"
    section = m.group(1)
    rows = set(re.findall(r"^\|\s*`([a-z_]+)`\s*\|", section,
                          re.MULTILINE))
    expected = set(lifecycle.BREAKER_DOMAINS) | set(
        lifecycle.BREAKER_STATES)
    assert rows == expected, (
        f"docs/robustness.md breaker tables drifted: "
        f"missing={sorted(expected - rows)} "
        f"stale={sorted(rows - expected)}")


def test_adaptive_decisions_table_matches_registry():
    """docs/robustness.md's adaptive-execution decision table lists
    exactly exec.adaptive.DECISIONS (ISSUE 19: the same drift lint the
    breaker-domain table gets), scoped to the adaptive section."""
    from spark_rapids_tpu.exec import adaptive
    docs = (ROOT / "docs" / "robustness.md").read_text()
    m = re.search(r"## Adaptive execution\n(.*?)(?:\n## |\Z)",
                  docs, re.DOTALL)
    assert m, "docs/robustness.md lost its adaptive-execution section"
    rows = set(re.findall(r"^\|\s*`([a-z_]+)`\s*\|", m.group(1),
                          re.MULTILINE))
    expected = set(adaptive.DECISIONS)
    assert rows == expected, (
        f"docs/robustness.md adaptive decision table drifted: "
        f"missing={sorted(expected - rows)} "
        f"stale={sorted(rows - expected)}")


def test_workload_tables_match_registry():
    """docs/robustness.md's workload-governor admission-state and
    priority tables list exactly workload.ADMISSION_STATES /
    PRIORITIES (ISSUE 7: the same drift lint the breaker tables get),
    scoped to the governor section."""
    from spark_rapids_tpu.exec import workload
    docs = (ROOT / "docs" / "robustness.md").read_text()
    m = re.search(r"## Concurrent workload governor\n(.*?)(?:\n## |\Z)",
                  docs, re.DOTALL)
    assert m, "docs/robustness.md lost its workload-governor section"
    section = m.group(1)
    rows = set(re.findall(r"^\|\s*`([a-z_]+)`\s*\|", section,
                          re.MULTILINE))
    expected = set(workload.ADMISSION_STATES) | set(workload.PRIORITIES)
    assert rows == expected, (
        f"docs/robustness.md workload tables drifted: "
        f"missing={sorted(expected - rows)} "
        f"stale={sorted(rows - expected)}")


def test_stall_actions_table_matches_registry():
    """docs/robustness.md's stall-action table lists exactly
    speculation_shield.STALL_ACTIONS (ISSUE 20: the breaker-table drift
    discipline for the progress watchdog's closed action set), scoped
    to the shield section."""
    from spark_rapids_tpu.exec import speculation_shield
    docs = (ROOT / "docs" / "robustness.md").read_text()
    m = re.search(r"## Straggler & stall shield\n(.*?)(?:\n## |\Z)",
                  docs, re.DOTALL)
    assert m, "docs/robustness.md lost its straggler-shield section"
    # the action table nests inside the watchdog bullet, so rows carry
    # the bullet's indent
    rows = set(re.findall(r"^\s*\|\s*`([a-z][a-z-]*)`\s*\|", m.group(1),
                          re.MULTILINE))
    expected = set(speculation_shield.STALL_ACTIONS)
    assert rows == expected, (
        f"docs/robustness.md stall-action table drifted: "
        f"missing={sorted(expected - rows)} "
        f"stale={sorted(rows - expected)}")


def test_robustness_event_kinds_are_registered():
    """Every event kind the robustness layer emits is in
    obs.events.EVENT_LEVELS (an unregistered kind silently defaults to
    MODERATE — fine at runtime, but the schema table must know it)."""
    from spark_rapids_tpu.obs import events
    for kind in ("fault_inject", "io_retry", "task_retry",
                 "integrity_fail", "pipeline_stuck", "spill_error",
                 "spill_writer_dead", "query_cancelled",
                 "task_retry_settle_error", "partition_recompute",
                 "breaker_open", "breaker_half_open", "breaker_close",
                 "peer_dead", "query_queued", "query_admitted",
                 "query_shed", "quota_spill"):
        assert kind in events.EVENT_LEVELS, kind
    docs = (ROOT / "docs" / "observability.md").read_text()
    for kind in events.EVENT_LEVELS:
        assert f"`{kind}`" in docs, (
            f"event kind {kind} missing from docs/observability.md")


def test_telemetry_series_table_matches_registry():
    """docs/observability.md's telemetry series table lists exactly
    obs.telemetry.SERIES (ISSUE 11: the same drift lint EVENT_LEVELS /
    CANONICAL_METRICS get), scoped to the telemetry section so other
    name tables in the doc can't collide."""
    from spark_rapids_tpu.obs import telemetry
    docs = (ROOT / "docs" / "observability.md").read_text()
    m = re.search(r"## Telemetry registry\n(.*?)(?:\n## |\Z)", docs,
                  re.DOTALL)
    assert m, "docs/observability.md lost its telemetry section"
    rows = set(re.findall(r"^\|\s*`([a-z_]+\.[a-z_0-9]+)`\s*\|",
                          m.group(1), re.MULTILINE))
    expected = set(telemetry.SERIES)
    assert rows == expected, (
        f"docs/observability.md telemetry table drifted: "
        f"missing={sorted(expected - rows)} "
        f"stale={sorted(rows - expected)}")


def test_statistics_event_kinds_are_registered():
    """The runtime-statistics plane's event kinds are registered in
    EVENT_LEVELS (the ISSUE 4/6/7 pattern) — the docs-row half is
    covered by test_robustness_event_kinds_are_registered's full
    EVENT_LEVELS sweep."""
    from spark_rapids_tpu.obs import events
    for kind in ("exchange_stats", "telemetry_sample"):
        assert kind in events.EVENT_LEVELS, kind


def test_pallas_family_registries_agree():
    """Every Pallas kernel family (ops/pallas_tier.PALLAS_FAMILIES)
    appears in (1) lifecycle.FAMILY_DOMAINS so the circuit breakers can
    demote it, (2) tools/kern_bench.py's BENCHES so `auto` selection is
    a measurement, and (3) the docs/perf.md tier table — and none of
    the three registries carries a stale family (ISSUE 8: the three
    drifted silently before measurement-gating existed)."""
    import sys
    from spark_rapids_tpu.exec import lifecycle
    from spark_rapids_tpu.ops import pallas_tier

    fams = set(pallas_tier.PALLAS_FAMILIES)
    assert fams == set(lifecycle.FAMILY_DOMAINS), (
        f"FAMILY_DOMAINS drifted: "
        f"missing={sorted(fams - set(lifecycle.FAMILY_DOMAINS))} "
        f"stale={sorted(set(lifecycle.FAMILY_DOMAINS) - fams)}")
    # every family's breaker domain is a registered breaker
    for fam, dom in lifecycle.FAMILY_DOMAINS.items():
        assert dom in lifecycle.BREAKER_DOMAINS, (fam, dom)

    sys.path.insert(0, str(ROOT / "tools"))
    try:
        import kern_bench
    finally:
        sys.path.pop(0)
    assert fams == set(kern_bench.BENCHES), (
        f"kern_bench families drifted: "
        f"missing={sorted(fams - set(kern_bench.BENCHES))} "
        f"stale={sorted(set(kern_bench.BENCHES) - fams)}")
    for fam in fams:
        assert fam in kern_bench.DEFAULT_SHAPES, fam
        assert fam in kern_bench.QUICK_SHAPES, fam

    docs = (ROOT / "docs" / "perf.md").read_text()
    m = re.search(r"## Pallas kernel family tier table\n(.*?)(?:\n## |\Z)",
                  docs, re.DOTALL)
    assert m, "docs/perf.md lost its Pallas family tier table"
    rows = set(re.findall(r"^\|\s*`([a-z_0-9]+)`\s*\|", m.group(1),
                          re.MULTILINE))
    assert rows == fams, (
        f"docs/perf.md tier table drifted: "
        f"missing={sorted(fams - rows)} stale={sorted(rows - fams)}")


def test_fusion_whitelist_table_matches_registry():
    """docs/perf.md's fusion-whitelist table lists exactly
    exec/stage_compiler.FUSABLE_OPS (ISSUE 14) — the tier-table drift
    lint pattern: an operator added to (or dropped from) the stage
    compiler without its docs row fails tier-1."""
    from spark_rapids_tpu.exec.stage_compiler import FUSABLE_OPS
    docs = (ROOT / "docs" / "perf.md").read_text()
    m = re.search(r"### Fusion whitelist\n(.*?)(?:\n#|\Z)", docs,
                  re.DOTALL)
    assert m, "docs/perf.md lost its fusion-whitelist table"
    rows = set(re.findall(r"^\|\s*`([A-Za-z0-9_]+Exec)`\s*\|",
                          m.group(1), re.MULTILINE))
    expected = set(FUSABLE_OPS)
    assert rows == expected, (
        f"docs/perf.md fusion-whitelist table drifted: "
        f"missing={sorted(expected - rows)} "
        f"stale={sorted(rows - expected)}")


def test_additional_metrics_are_canonical_and_unique():
    classes = _all_exec_classes()
    assert len(classes) >= 20  # the walk actually found the exec tree
    problems = []
    valid_levels = {exec_base.ESSENTIAL, exec_base.MODERATE,
                    exec_base.DEBUG}
    for cls in classes:
        try:
            # the contract this lint enforces includes additional_metrics
            # being a static declaration (no self state)
            specs = list(cls.additional_metrics(None))
        except Exception as e:  # noqa: BLE001
            problems.append(f"{cls.__name__}.additional_metrics must be "
                            f"self-independent (got {type(e).__name__})")
            continue
        names = []
        for spec in specs:
            name, level = spec if isinstance(spec, tuple) \
                else (spec, exec_base.MODERATE)
            names.append(name)
            if name not in exec_base.CANONICAL_METRICS:
                problems.append(
                    f"{cls.__name__}: metric {name!r} is not canonical — "
                    "add it to exec.base.CANONICAL_METRICS or reuse an "
                    "existing name")
            if level not in valid_levels:
                problems.append(f"{cls.__name__}: metric {name!r} has "
                                f"invalid level {level!r}")
        if len(names) != len(set(names)):
            problems.append(f"{cls.__name__}: duplicate metric names "
                            f"{names}")
    assert not problems, "\n".join(problems)


def test_phase_table_matches_registry():
    """docs/observability.md's wall-clock phase table lists exactly
    obs.phase.PHASES (ISSUE 17: the same drift lint the telemetry
    series / event-kind tables get), scoped to the phase section."""
    from spark_rapids_tpu.obs import phase
    docs = (ROOT / "docs" / "observability.md").read_text()
    m = re.search(r"## Wall-clock phase attribution\n(.*?)(?:\n## |\Z)",
                  docs, re.DOTALL)
    assert m, "docs/observability.md lost its phase-attribution section"
    rows = set(re.findall(r"^\|\s*`([a-z][a-z-]*)`\s*\|", m.group(1),
                          re.MULTILINE))
    expected = set(phase.PHASES)
    assert rows == expected, (
        f"docs/observability.md phase table drifted: "
        f"missing={sorted(expected - rows)} "
        f"stale={sorted(rows - expected)}")


def test_advisor_rules_table_matches_registry():
    """docs/robustness.md's advisor-rules table lists exactly the
    history_report.ADVISOR_RULES ids (ISSUE 17: the fault-point
    discipline for the advisor's closed rule registry)."""
    sys.path.insert(0, str(ROOT / "tools"))
    try:
        import history_report
    finally:
        sys.path.pop(0)
    docs = (ROOT / "docs" / "robustness.md").read_text()
    m = re.search(r"## Advisor rules\n(.*?)(?:\n## |\Z)", docs,
                  re.DOTALL)
    assert m, "docs/robustness.md lost its advisor-rules section"
    rows = set(re.findall(r"^\|\s*`([a-z][a-z-]*)`\s*\|", m.group(1),
                          re.MULTILINE))
    expected = {r.id for r in history_report.ADVISOR_RULES}
    assert rows == expected, (
        f"docs/robustness.md advisor table drifted: "
        f"missing={sorted(expected - rows)} "
        f"stale={sorted(rows - expected)}")


def test_canonical_metrics_table_matches_registry():
    """docs/observability.md's canonical-metrics table has one row per
    exec.base.CANONICAL_METRICS name (ISSUE 17 satellite: the metric
    registry gets the same docs lint its consumers always had), scoped
    to the canonical-metrics section."""
    docs = (ROOT / "docs" / "observability.md").read_text()
    m = re.search(r"## Canonical metrics\n(.*?)(?:\n## |\Z)", docs,
                  re.DOTALL)
    assert m, "docs/observability.md lost its canonical-metrics section"
    rows = set(re.findall(r"^\|\s*`([a-zA-Z]+)`\s*\|", m.group(1),
                          re.MULTILINE))
    expected = set(exec_base.CANONICAL_METRICS)
    assert rows == expected, (
        f"docs/observability.md canonical-metrics table drifted: "
        f"missing={sorted(expected - rows)} "
        f"stale={sorted(rows - expected)}")
