"""Headline benchmarks on one chip: q1-style aggregation + q3-style join.

Runs the flagship pipeline (filter -> derived projection -> group-by
aggregate, the TPC-H q1 shape) through the full exec layer (spillable
batches, retry guards, planner-built operators) on the default jax backend
and compares steady-state wall-clock against a vectorized numpy oracle of
the same query.

Timing methodology: the engine's steady-state hot path is sync-free — row
counts, collision flags and merge decisions all stay on device — so the
timed loop runs ITERS full pipelines back-to-back with a device-side
checksum chained across iterations (each checksum consumes the previous
one, so no iteration can be elided), and the clock stops on the ONE d2h
fetch of the final checksum, which forces completion of every queued
program. Result correctness is verified against the numpy oracle after the
clock stops, and the checksum is cross-checked against the fetched result
so all ITERS iterations are proven to have produced it.

Prints one JSON line per lane: {"metric", "value", "unit",
"vs_baseline"}. The q1 lane (headline) prints FIRST. The q3 lane runs
the scan -> filter -> hash join -> group-by -> top-N shape through the
exec layer's EXACT aggregation tier (orderkey cardinality is far past
the speculative bucket table) so join+sort regressions are visible to
the driver loop (round-2 verdict item 9).
"""

import json
import os
import sys
import threading
import time

import numpy as np


def maybe_enable_faults(argv=None):
    """`bench.py --fault-rate R` (ISSUE 4 satellite): run the standard
    bench under seeded chaos injection at every registered fault point
    with per-call probability R, so nightly rounds track recovery
    overhead alongside throughput. The seed comes from
    SPARK_RAPIDS_TPU_FAULT_SEED (default 42) — a failing chaos round
    replays exactly. Returns the rate (None = injection off)."""
    global _FAULT_RATE
    argv = sys.argv if argv is None else argv
    if "--fault-rate" not in argv:
        return None
    idx = argv.index("--fault-rate")
    try:
        rate = float(argv[idx + 1])
    except (IndexError, ValueError):
        print(json.dumps({"error_kind": "usage",
                          "error": "--fault-rate requires a numeric "
                                   "probability argument"}))
        raise SystemExit(2)
    seed = int(os.environ.get("SPARK_RAPIDS_TPU_FAULT_SEED", "42"))
    from spark_rapids_tpu import faults
    faults.install(faults.uniform_spec(rate, seed))
    _FAULT_RATE = rate
    return rate


_FAULT_RATE = None

#: per-lane query deadline (--query-timeout-ms): every guarded_run
#: iteration runs under a lifecycle QueryContext with this deadline, so
#: a chaos soak proves BOUNDED per-query wall-clock, not just eventual
#: convergence (ISSUE 6)
_QUERY_TIMEOUT_MS = None


def maybe_query_timeout(argv=None):
    """`bench.py --query-timeout-ms N`: run every bench iteration under
    the lifecycle governor with an N-ms deadline (exec/lifecycle.py). A
    lane that would exceed it raises QueryCancelledError and fails
    loudly instead of wedging a nightly round. Returns the timeout
    (None = no deadline)."""
    global _QUERY_TIMEOUT_MS
    argv = sys.argv if argv is None else argv
    if "--query-timeout-ms" not in argv:
        return None
    idx = argv.index("--query-timeout-ms")
    try:
        ms = int(argv[idx + 1])
        assert ms > 0
    except (IndexError, ValueError, AssertionError):
        print(json.dumps({"error_kind": "usage",
                          "error": "--query-timeout-ms requires a "
                                   "positive integer millisecond "
                                   "argument"}))
        raise SystemExit(2)
    _QUERY_TIMEOUT_MS = ms
    return ms


#: `bench.py --stage-fusion on|off` (ISSUE 14): A/B the whole-stage
#: compiler on the handmade lane plans. Default (None) follows the
#: conf (stage.fusion.enabled, default on).
_STAGE_FUSION = None


def maybe_stage_fusion(argv=None):
    """Parse `--stage-fusion on|off`. Bad argv emits the usage-error
    JSON convention and exits 2 — never a traceback."""
    global _STAGE_FUSION
    argv = sys.argv if argv is None else argv
    if "--stage-fusion" not in argv:
        return None
    idx = argv.index("--stage-fusion")
    try:
        mode = argv[idx + 1]
        assert mode in ("on", "off")
    except (IndexError, AssertionError):
        print(json.dumps({"error_kind": "usage",
                          "error": "--stage-fusion requires 'on' or "
                                   "'off'"}))
        raise SystemExit(2)
    _STAGE_FUSION = mode == "on"
    from spark_rapids_tpu.config import (RapidsConf, active_conf,
                                         set_active_conf)
    settings = dict(active_conf()._settings)
    settings["spark.rapids.tpu.stage.fusion.enabled"] = str(
        _STAGE_FUSION).lower()
    set_active_conf(RapidsConf(settings))
    return _STAGE_FUSION


def compile_lane_plan(plan):
    """Route a handmade lane's exec tree through the stage planner
    (ISSUE 14) — the same rewrite DataFrame._exec applies to planner-
    built trees; a no-op with fusion off, so `--stage-fusion off` is
    the per-operator baseline."""
    from spark_rapids_tpu.exec.stage_compiler import compile_stages
    return compile_stages(plan)


def stage_attribution():
    """{"stage": ...} block for each BENCH record (ISSUE 14): stages
    fused, operators absorbed, fused-stage program dispatches and
    plan-fingerprint program-cache hits this lane generated
    (exec/stage_compiler.py + obs/dispatch.py counters, as deltas
    since the previous record; the _delta_since pattern). All zeros
    with --stage-fusion off — a round reads dispatches next to the
    q1/q3 throughput to see the per-operator overhead collapse."""
    from spark_rapids_tpu.exec import stage_compiler
    cur = stage_compiler.counters()
    return _delta_since("stage", {
        "stages_fused": cur["stages_fused"],
        "ops_fused": cur["ops_fused"],
        "dispatches": cur["dispatches"],
        "cache_hits": cur["cache_hits"]})


#: `bench.py --concurrency N` (ISSUE 7): drive each lane from N
#: threads, every iteration admitted through the workload governor —
#: the nightly proof that fair admission + per-query quotas compose
#: with the recovery lanes under real contention
_CONCURRENCY = 1


def maybe_concurrency(argv=None):
    """Parse `--concurrency N` (N >= 1 lane threads). Bad argv emits
    the usage-error JSON convention and exits 2 — never a traceback."""
    global _CONCURRENCY
    argv = sys.argv if argv is None else argv
    if "--concurrency" not in argv:
        return None
    idx = argv.index("--concurrency")
    try:
        n = int(argv[idx + 1])
        assert n >= 1
    except (IndexError, ValueError, AssertionError):
        print(json.dumps({"error_kind": "usage",
                          "error": "--concurrency requires a positive "
                                   "integer thread-count argument"}))
        raise SystemExit(2)
    _CONCURRENCY = n
    return n


def run_concurrent(worker):
    """Run worker(i) once (concurrency 1: exactly the single-lane
    path), or from N threads under --concurrency N. Re-raises the first
    worker failure so a broken lane fails the round loudly."""
    n = _CONCURRENCY
    if n <= 1:
        return [worker(0)]
    results = [None] * n
    errors = [None] * n

    def drive(i):
        try:
            results[i] = worker(i)
        except BaseException as e:  # noqa: BLE001 — re-raised below
            errors[i] = e

    threads = [threading.Thread(target=drive, args=(i,),
                                name=f"bench-lane-{i}") for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for e in errors:
        if e is not None:
            raise e
    return results


#: per-family counter snapshots for the attribution blocks below —
#: the underlying counters are process-cumulative, each BENCH record
#: must report only ITS OWN lane's deltas (the chaos-delta pattern,
#: ONE implementation shared by every flat counter family)
_attr_prev = {}


def _delta_since(family, cur):
    prev = _attr_prev.get(family, {})
    _attr_prev[family] = cur
    return {k: v - prev.get(k, 0) for k, v in cur.items()}


def workload_attribution():
    """{"workload": ...} block for each BENCH record: admissions,
    queue residency, sheds and quota spills this lane generated
    (exec/workload.py counters, as deltas since the previous record)."""
    from spark_rapids_tpu.exec import workload
    out = _delta_since("workload", workload.counters())
    out["concurrency"] = _CONCURRENCY
    return out


def lifecycle_attribution():
    """{"lifecycle": ...} block for each BENCH record: cancellations,
    breaker transitions and partition-vs-whole-plan recovery counts
    this lane absorbed (exec/lifecycle.py counters, as deltas since the
    previous record)."""
    from spark_rapids_tpu.exec import lifecycle
    out = _delta_since("lifecycle", lifecycle.counters())
    if _QUERY_TIMEOUT_MS is not None:
        out["query_timeout_ms"] = _QUERY_TIMEOUT_MS
    return out


def gather_attribution():
    """{"gather": ...} block for each BENCH record: materializing row
    gathers this lane dispatched, how many rode a packed (multi-column)
    row gather, and the estimated bytes moved (ops/gather.py counters,
    as deltas since the previous record). pallas_count distinguishes
    DMA-kernel-served gathers from the XLA fallback — without it a
    throughput delta can't be attributed."""
    from spark_rapids_tpu.ops import gather as gather_engine
    return _delta_since("gather", gather_engine.counters())


def shuffle_attribution():
    """{"shuffle": ...} block for each BENCH record (ISSUE 9): batches
    split per lane (device vs host), frames/bytes written, host-side
    row gathers (0 on the device-partition lanes), and the write-time
    split pack/serialize/IO (shuffle/manager.py counters, as deltas
    since the previous record). Lanes that never shuffle report zeros —
    the block is present in every record so a round can assert the
    device lane actually engaged."""
    from spark_rapids_tpu.shuffle import manager as shuffle_mgr
    return _delta_since("shuffle", shuffle_mgr.counters())


#: `bench.py --shuffle-mode ici|host` (ISSUE 16): pin the ICI
#: device-resident shuffle lane on or off for the whole run. Default
#: (None) follows the conf (shuffle.ici.enabled, default off).
_SHUFFLE_MODE = None


def maybe_shuffle_mode(argv=None):
    """Parse `--shuffle-mode ici|host`. Bad argv emits the usage-error
    JSON convention and exits 2 — never a traceback."""
    global _SHUFFLE_MODE
    argv = sys.argv if argv is None else argv
    if "--shuffle-mode" not in argv:
        return None
    idx = argv.index("--shuffle-mode")
    try:
        mode = argv[idx + 1]
        assert mode in ("ici", "host")
    except (IndexError, AssertionError):
        print(json.dumps({"error_kind": "usage",
                          "error": "--shuffle-mode requires 'ici' or "
                                   "'host'"}))
        raise SystemExit(2)
    _SHUFFLE_MODE = mode
    from spark_rapids_tpu.config import (RapidsConf, active_conf,
                                         set_active_conf)
    settings = dict(active_conf()._settings)
    settings["spark.rapids.tpu.shuffle.ici.enabled"] = str(
        mode == "ici").lower()
    set_active_conf(RapidsConf(settings))
    return _SHUFFLE_MODE


def ici_attribution():
    """{"ici": ...} block for each BENCH record (ISSUE 16): exchange
    rounds the ICI device-resident lane ran, map batches and bytes it
    moved over the collective, collective wall-ns and host-lane
    fallbacks (shuffle/manager.py ici_counters, as deltas since the
    previous record). Zeros with --shuffle-mode host (or off-mesh lanes
    that never shuffle) — the block is present in every record so a pod
    round can assert the ICI lane actually engaged, and read the
    serialize frames collapse in the neighboring shuffle block."""
    from spark_rapids_tpu.shuffle import manager as shuffle_mgr
    out = _delta_since("ici", shuffle_mgr.ici_counters())
    if _SHUFFLE_MODE is not None:
        out["mode"] = _SHUFFLE_MODE
    return out


#: counter snapshot at the previous chaos_attribution() call — the
#: underlying counters are process-cumulative, each BENCH record must
#: report only ITS OWN lane's deltas
_chaos_prev = {"points": {}, "io": 0, "task": 0}


def chaos_attribution():
    """{"chaos": ...} block for each BENCH record under --fault-rate:
    which points fired DURING THIS LANE, and how many recoveries each
    layer (IO retry / task re-execution) absorbed to keep it green."""
    global _chaos_prev
    if _FAULT_RATE is None:
        return None
    from spark_rapids_tpu import faults
    from spark_rapids_tpu.exec.task_retry import task_retry_total
    from spark_rapids_tpu.io.retrying import io_retry_recoveries
    points = faults.stats()
    io_rec, task_rec = io_retry_recoveries(), task_retry_total()
    prev = _chaos_prev
    points_hit = {p: c - prev["points"].get(p, 0)
                  for p, c in points.items()
                  if c - prev["points"].get(p, 0)}
    rec = {
        "fault_rate": _FAULT_RATE,
        "points_hit": points_hit,
        "injections": sum(points_hit.values()),
        "recoveries": {"io_retry": io_rec - prev["io"],
                       "task_retry": task_rec - prev["task"]},
        "task_retries": task_rec - prev["task"],
    }
    _chaos_prev = {"points": points, "io": io_rec, "task": task_rec}
    return rec


#: cached chaos/workload conf overlays, keyed by (base conf identity,
#: the argv-derived flags): guarded_run sits inside each lane's timed
#: steady-state loop — rebuilding the settings dict + RapidsConf per
#: iteration would charge the concurrency metric overhead the
#: single-lane baseline never pays
_overlay_cache = {}


def _overlaid_conf():
    from spark_rapids_tpu.config import RapidsConf, active_conf
    base = active_conf()
    # the conf OBJECT in the key (identity hash) pins it: an id()-only
    # key could alias a recycled address after the base is collected
    key = (base, _FAULT_RATE, _CONCURRENCY)
    cached = _overlay_cache.get(key)
    if cached is not None:
        return cached
    settings = dict(base._settings)
    if _FAULT_RATE is not None:
        # OVERLAY on the active conf, don't replace it: a chaos round
        # that set task.retryBackoffMs must keep it, or retry sleeps
        # land inside the timed loops at the 100ms default
        settings["spark.rapids.tpu.task.maxAttempts"] = "20"
    if _CONCURRENCY > 1:
        # --concurrency N: every iteration is admitted through the
        # workload governor (exec/workload.py) — maxConcurrentQueries
        # at half the lane threads forces real queue residency, the
        # queue depth keeps honest lanes from ever being shed
        settings.update({
            "spark.rapids.tpu.workload.enabled": "true",
            "spark.rapids.tpu.workload.maxConcurrentQueries":
                str(max(1, _CONCURRENCY // 2)),
            "spark.rapids.tpu.workload.queueDepth":
                str(max(16, 2 * _CONCURRENCY))})
    cached = RapidsConf(settings)
    _overlay_cache[key] = cached
    return cached


def guarded_run(fn):
    """Run one bench iteration under the task-attempt layer: a
    transient failure (injected or real) re-executes the iteration
    instead of killing the lane. With injection off this is one
    function call of overhead.

    maxAttempts is raised well past the session default: chaos arming
    here is prob-only (no per-point max caps — nightly rounds want a
    SUSTAINED injection rate, not a budget that runs dry mid-lane), so
    convergence is probabilistic. The plan's call indexes advance across
    attempts, each retry faces fresh seeded draws, and at 20 attempts
    even a 50% per-attempt kill rate fails a lane ~1e-6 of the time."""
    from spark_rapids_tpu.config import active_conf
    from spark_rapids_tpu.exec.task_retry import with_task_retry
    conf = _overlaid_conf() \
        if _FAULT_RATE is not None or _CONCURRENCY > 1 else None
    if _QUERY_TIMEOUT_MS is not None or _CONCURRENCY > 1:
        # --query-timeout-ms: the deadline spans the iteration's whole
        # retry chain (exec/lifecycle.py), proving bounded per-query
        # wall-clock under chaos instead of just eventual convergence;
        # the governed context also carries the workload ticket
        from spark_rapids_tpu.exec import lifecycle, workload
        base = conf if conf is not None else active_conf()
        with lifecycle.governed(base,
                                timeout_ms=_QUERY_TIMEOUT_MS) as ctx:
            with workload.admitted(base, ctx):
                return with_task_retry(lambda attempt: fn(), conf=conf)
    return with_task_retry(lambda attempt: fn(), conf=conf)


def maybe_enable_event_log():
    """Opt-in structured event log for bench runs: set
    SPARK_RAPIDS_TPU_EVENTLOG_DIR to get a JSONL operator-span log
    (obs/events.py) next to the BENCH records; render it with
    tools/profile_report.py. SPARK_RAPIDS_TPU_EVENTLOG_MAX_BYTES
    rotates the sink so a bench storm never grows one unbounded file.
    Default: off, zero per-batch cost."""
    d = os.environ.get("SPARK_RAPIDS_TPU_EVENTLOG_DIR")
    if d:
        from spark_rapids_tpu.obs import events
        events.enable(d, os.environ.get("SPARK_RAPIDS_TPU_EVENTLOG_LEVEL",
                                        "MODERATE"),
                      max_bytes=int(os.environ.get(
                          "SPARK_RAPIDS_TPU_EVENTLOG_MAX_BYTES", "0")))


def maybe_enable_history():
    """Opt-in query-history capsules for bench runs (ISSUE 17): set
    SPARK_RAPIDS_TPU_HISTORY_DIR to append one JSONL capsule per
    governed query (obs/history.py) — two bench runs into separate
    dirs, then `tools/history_report.py CUR --diff BASE` ranks any
    regression by the phase that moved.
    SPARK_RAPIDS_TPU_HISTORY_MAX_BYTES rotates the capsule file.
    Default: off, one pointer check per collect."""
    d = os.environ.get("SPARK_RAPIDS_TPU_HISTORY_DIR")
    if d:
        from spark_rapids_tpu.obs import history
        history.enable(d, max_bytes=int(os.environ.get(
            "SPARK_RAPIDS_TPU_HISTORY_MAX_BYTES", "0")))


def phases_attribution():
    """{"phases": ...} block for each BENCH record (ISSUE 17): the
    process-cumulative wall-clock phase counters (obs/phase.py) as
    deltas since the previous record — which phases this lane's wall
    went to, even for lanes that drive plan.execute() directly with no
    governed query (where no per-query ledger exists)."""
    from spark_rapids_tpu.obs import phase
    return _delta_since("phases", phase.counters())


def maybe_enable_telemetry():
    """Opt-in live telemetry for bench runs (ISSUE 11): set
    SPARK_RAPIDS_TPU_TELEMETRY_MS to a sampling interval to start the
    registry + sampler thread; samples flush into the event log (when
    enabled above) as telemetry_sample records — render with
    tools/telemetry_export.py. Default: off, one pointer check per
    push site."""
    ms = os.environ.get("SPARK_RAPIDS_TPU_TELEMETRY_MS")
    if ms:
        from spark_rapids_tpu.obs import telemetry
        telemetry.enable(interval_ms=int(ms))


def query_attribution(plan, before):
    """Per-operator attribution embedded in each BENCH record (ISSUE 2:
    BENCH deltas stop being single scalar GB/s numbers): the
    GpuTaskMetrics-style per-query summary + top operators by time."""
    try:
        from spark_rapids_tpu.obs.profile import bench_profile_summary
        return bench_profile_summary(plan, before)
    except Exception as e:  # noqa: BLE001 — attribution must never
        return {"error": f"{type(e).__name__}: {e}"[:200]}  # kill a lane

def upload_attribution():
    """{"upload": ...} block for each BENCH record (ISSUE 10): batch
    uploads per lane (packed = one transfer | per-buffer), actual
    host->device transfers dispatched, bytes moved, pack+transfer time
    and staging-pool hit/miss counts (columnar/upload.py counters, as
    deltas since the previous record). Lanes that never ingest report
    zeros — the block is present in every record so a TPU round can
    assert the packed lane actually engaged."""
    from spark_rapids_tpu.columnar import upload as upload_engine
    return _delta_since("upload", upload_engine.counters())


def encoded_attribution():
    """{"encoded": ...} block for each BENCH record (ISSUE 18):
    dictionary-encoded lane activity — columns kept encoded at the
    scan, code/dictionary byte split, eager-decode bytes avoided,
    late materializations (and their bytes), code-space predicates
    and dictionary hash tables served (columnar/encoded.py counters,
    as deltas since the previous record). All zeros with
    scan.encoded.enabled=false — a TPU round reads
    decoded_bytes_avoided next to the upload block to see the H2D
    shrink the encoded lane bought."""
    from spark_rapids_tpu.columnar import encoded as encoded_engine
    return _delta_since("encoded", encoded_engine.counters())


def adaptive_attribution():
    """{"adaptive": ...} block for each BENCH record (ISSUE 19):
    runtime-replanner activity — exchange consults, skew splits,
    broadcast demotions, single-build conversions, partition
    coalesces, OOM batch right-sizings, breaker stand-downs and lane
    errors (exec/adaptive.py counters, as deltas since the previous
    record). All zeros with adaptive.enabled=false — a round compares
    the on/off delta next to shuffle/statistics to see what acting on
    the measured sizes actually bought."""
    from spark_rapids_tpu.exec import adaptive as adaptive_engine
    return _delta_since("adaptive", adaptive_engine.counters())


def speculation_attribution():
    """{"speculation": ...} block for each BENCH record (ISSUE 20):
    straggler-shield activity — stall episodes and their actions,
    speculative sub-reads launched/won/denied, post-bound wait ns,
    dispatch-timeout trips, dead-peer invalidations
    (exec/speculation_shield.py counters, as deltas since the previous
    record). All zeros with the shield's confs at defaults — a chaos
    round with delay injection reads spec_wins next to shuffle to see
    what racing the tail bought."""
    from spark_rapids_tpu.exec import speculation_shield
    return _delta_since("speculation", speculation_shield.counters())


def dispatch_attribution():
    """{"dispatch": ...} block for each BENCH record (ISSUE 13):
    compiled programs, program dispatches, fresh traces vs jit cache
    hits, compile wall-ns and recompile storms this lane generated
    (obs/dispatch.py ledger counters, as deltas since the previous
    record). All zeros with dispatch.ledger.enabled=false — a TPU
    round reads dispatches/compile_ns next to throughput to see what
    whole-stage compilation (ROADMAP 2) must collapse."""
    from spark_rapids_tpu.obs import dispatch as dispatch_ledger
    cur = dispatch_ledger.counters()
    return _delta_since("dispatch",
                        {"programs": cur["programs"],
                         "dispatches": cur["dispatches"],
                         "compile_ns": cur["compile_ns"],
                         "cache_hits": cur["cache_hits"],
                         "storms": cur["storms"]})


def telemetry_attribution():
    """{"telemetry": ...} block for each BENCH record (ISSUE 11):
    registry activity (samples taken, registry writes, push counters)
    this lane generated, as deltas since the previous record — all
    zeros with telemetry off, so a round can assert the plane actually
    engaged."""
    from spark_rapids_tpu.obs import telemetry
    return _delta_since("telemetry", telemetry.counters())


def statistics_attribution():
    """{"statistics": ...} block for each BENCH record (ISSUE 11):
    exchange map outputs/bytes this lane wrote (deltas, chaos-delta
    pattern) plus the point-in-time distribution summary — the p95
    map-output bytes and last observed partition skew ratio — so an
    accumulated TPU round reads skew/attribution next to throughput.
    Lanes that never shuffle report zeros; the block is present in
    every record."""
    from spark_rapids_tpu.obs import stats as runtime_stats
    cur = runtime_stats.counters()
    out = _delta_since("statistics",
                       {"maps": cur["maps"], "bytes": cur["bytes"]})
    out["p95_map_output_bytes"] = cur["p95_map_output_bytes"]
    out["skew_ratio"] = cur["skew_ratio_x1000"] / 1000.0
    return out


def pipeline_attribution():
    """{"pipeline": ...} block for each BENCH record (ISSUE 3
    satellite): the synthetic slow-producer/slow-consumer overlap
    microbench (tools/pipeline_bench.py), run once per process — cheap
    (<1s) and device-free, it tracks whether the bounded stage boundary
    still buys its overlap on this host alongside the engine numbers."""
    global _PIPELINE_SUMMARY
    if _PIPELINE_SUMMARY is None:
        try:
            sys.path.insert(0, os.path.join(
                os.path.dirname(os.path.abspath(__file__)), "tools"))
            from pipeline_bench import run_bench
            _PIPELINE_SUMMARY = run_bench(items=30, produce_s=0.01,
                                          consume_s=0.01, depth=2)
        except Exception as e:  # noqa: BLE001 — attribution must never
            _PIPELINE_SUMMARY = {  # kill a lane
                "error": f"{type(e).__name__}: {e}"[:200]}
    return _PIPELINE_SUMMARY


_PIPELINE_SUMMARY = None

ROWS = 1 << 24  # 16M rows, ~448 MB
BATCHES = 1
ITERS = 30

#: backend-init retry policy (VERDICT r5 Weak #1): one transient
#: axon/relay outage must not zero a round's perf record
INIT_ATTEMPTS = 3
INIT_BACKOFF_S = 2.0


def with_backend_retry(fn, attempts: int = INIT_ATTEMPTS,
                       base_sleep: float = INIT_BACKOFF_S,
                       sleep=time.sleep, error_kind: str = "backend_init"):
    """Run `fn` with bounded exponential-backoff retry.

    On the final failure, emit a STRUCTURED error record on stdout —
    {"error_kind": "backend_init", ...} — and exit 0 instead of dying
    with a raw rc=1 traceback: the driver's perf log then records a
    machine-readable outage, not a zeroed round. Transient tunnel
    failures (the observed mode: the axon relay drops mid-init) recover
    on a later attempt and cost only the backoff sleep.
    """
    last = None
    for attempt in range(attempts):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 — init failures are opaque
            last = e
            if attempt < attempts - 1:
                sleep(base_sleep * (2 ** attempt))
    print(json.dumps({
        "error_kind": error_kind,
        "error": f"{type(last).__name__}: {last}"[:500],
        "attempts": attempts,
    }))
    raise SystemExit(0)


def backend_probe():
    """Import jax and force REAL backend initialization.

    `jax.devices()` alone is not enough: the axon/TPU plugin can
    enumerate devices and still fail at the first dispatched program
    ("TPU backend setup/compile error" inside `lax._convert_element_type`
    — the BENCH_r05 rc=1 mode, where the first cast of the data upload
    crashed OUTSIDE the retry guard). The probe therefore dispatches a
    tiny cast and blocks on its result, so every backend setup/compile
    failure surfaces HERE, inside with_backend_retry — and nowhere
    downstream gets wrapped, so a mid-run crash still fails loudly
    instead of being masked as an {"error_kind": ...} record."""
    import jax
    import jax.numpy as jnp
    assert jax.devices(), "no jax devices"
    jax.block_until_ready(
        jnp.arange(8, dtype=jnp.int32).astype(jnp.float32).sum())
    return jax


def init_backend(sleep=time.sleep):
    return with_backend_retry(backend_probe, sleep=sleep)


def build_data():
    rng = np.random.default_rng(0)
    return {
        "returnflag": rng.integers(0, 4, ROWS, dtype=np.int32),
        "quantity": rng.integers(1, 51, ROWS, dtype=np.int64),
        "extendedprice": rng.random(ROWS) * 1000.0,
        "discount": rng.random(ROWS) * 0.1,
    }


def numpy_oracle(d):
    keep = d["quantity"] <= 45
    flag = d["returnflag"][keep]
    qty = d["quantity"][keep]
    dp = (d["extendedprice"] * (1.0 - d["discount"]))[keep]
    out = {}
    for k in np.unique(flag):
        m = flag == k
        out[int(k)] = (int(qty[m].sum()), float(dp[m].sum()), int(m.sum()))
    return out


def _median_time(fn, reps=3):
    """Median-of-N oracle timing: one-shot numpy timings swung the
    recorded vs_baseline 389x->65x between rounds at near-identical
    engine GB/s (VERDICT r4 Weak #5) — the median makes the driver's
    trend line signal."""
    times = []
    out = None
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn()
        times.append(time.perf_counter() - t0)
    return out, sorted(times)[len(times) // 2]


def main():
    d = build_data()
    numpy_oracle(d)  # warm the page cache
    oracle, t_np = _median_time(lambda: numpy_oracle(d))

    jax = init_backend()
    import jax.numpy as jnp

    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.columnar.column import Column, bucket_capacity
    from spark_rapids_tpu.exec.aggregate import AggregateExec
    from spark_rapids_tpu.exec.basic import FilterExec, InMemoryScanExec, ProjectExec
    from spark_rapids_tpu.expr.aggexprs import Count, Sum
    from spark_rapids_tpu.expr.core import col, lit
    from spark_rapids_tpu.types import (
        DOUBLE, INT, LONG, Schema, StructField,
    )

    schema = Schema((
        StructField("returnflag", INT), StructField("quantity", LONG),
        StructField("extendedprice", DOUBLE), StructField("discount", DOUBLE),
    ))
    per = ROWS // BATCHES
    cap = bucket_capacity(per)
    batches = []
    for i in range(BATCHES):
        sl = slice(i * per, (i + 1) * per)
        cols = [Column.from_numpy(d[f.name][sl], f.data_type, capacity=cap)
                for f in schema.fields]
        batches.append(ColumnarBatch(cols, per, schema))

    def make_plan():
        scan = InMemoryScanExec(batches, schema)
        filt = FilterExec(col("quantity") <= lit(45), scan)
        proj = ProjectExec([
            col("returnflag"), col("quantity"),
            (col("extendedprice") * (lit(1.0) - col("discount")))
            .alias("disc_price")], filt)
        agg = AggregateExec(
            [col("returnflag")],
            [(Sum(col("quantity")), "sum_qty"),
             (Sum(col("disc_price")), "sum_disc"),
             (Count(), "cnt")], proj)
        # ISSUE 14: the scan->filter->project->agg chain compiles to
        # one fused stage (a no-op under --stage-fusion off)
        return compile_lane_plan(agg)

    from spark_rapids_tpu.exec.speculation import speculation_scope
    from spark_rapids_tpu.exec.task_metrics import query_snapshot

    metrics_before = query_snapshot()

    @jax.jit
    def checksum(batch, prev, spec_flags):
        total = prev + batch.num_rows.astype(jnp.float64)
        for c in batch.columns:
            v = jnp.where(c.validity, c.data, jnp.zeros((), c.data.dtype))
            total = total + jnp.sum(v).astype(jnp.float64)
        for f in spec_flags:
            # a tripped speculation flag poisons the checksum: no invalid
            # iteration can pass the final assertion
            total = total + jnp.where(f, jnp.nan, 0.0)
        return total

    def q1_lane(_i):
        # one plan per lane: exec instances own their compiled kernels,
        # so reuse across iterations exercises the steady-state compiled
        # path, while concurrent lanes never share operator state
        plan = make_plan()

        def run_once(prev, scope):
            outs = list(plan.execute())
            flags = tuple(scope.drain())
            chk = prev
            for b in outs:
                chk = checksum(b, chk, flags)
                flags = ()
            return outs, chk

        # warmup (compile + one full round trip); the with-block keeps
        # an assertion failure from leaking the thread-local scope into
        # later benchmarks in the same process
        with speculation_scope() as scope:
            outs, chk = guarded_run(
                lambda: run_once(jnp.float64(0.0), scope))
            rows = [r for b in outs for r in b.to_pylist()]
            got = {r[0]: (r[1], r[2], r[3]) for r in rows}
            for k, (sq, sd, c) in oracle.items():
                assert got[k][0] == sq and got[k][2] == c, \
                    (k, got[k], oracle[k])
                assert abs(got[k][1] - sd) / max(abs(sd), 1) < 1e-9
            expect_chk_1 = float(np.asarray(chk))

            # timed steady state: ITERS chained pipelines, ONE sync at
            # the end
            t0 = time.perf_counter()
            chk = jnp.float64(0.0)
            for _ in range(ITERS):
                _, chk = guarded_run(lambda c=chk: run_once(c, scope))
            final_chk = float(np.asarray(chk))  # completes all ITERS
            dt = (time.perf_counter() - t0) / ITERS

        # every iteration produced the verified result (telescoping)
        assert abs(final_chk - ITERS * expect_chk_1) <= \
            1e-9 * max(abs(final_chk), 1.0), \
            (final_chk, ITERS * expect_chk_1)
        return plan, dt

    lanes = run_concurrent(q1_lane)
    plan, dt = lanes[0]
    if _CONCURRENCY > 1:
        # aggregate the lanes' STEADY-STATE per-iteration rates (each
        # lane's timed loop ran concurrently with the others'): a wall
        # clock over the whole fan-out would fold every lane's jit
        # warmup and oracle verification into the metric and understate
        # it against the single-lane baseline
        dt = 1.0 / sum(1.0 / lane_dt for _plan, lane_dt in lanes)

    bytes_in = sum(v.nbytes for v in d.values())
    gbps = bytes_in / dt / 1e9
    rec = {
        "metric": "q1_agg_throughput",
        "value": round(gbps, 3),
        "unit": "GB/s",
        "vs_baseline": round(t_np / dt, 3),
        "profile": query_attribution(plan, metrics_before),
        "pipeline": pipeline_attribution(),
        "lifecycle": lifecycle_attribution(),
        "workload": workload_attribution(),
        "gather": gather_attribution(),
        "shuffle": shuffle_attribution(),
        "ici": ici_attribution(),
        "upload": upload_attribution(),
        "encoded": encoded_attribution(),
        "dispatch": dispatch_attribution(),
        "adaptive": adaptive_attribution(),
        "speculation": speculation_attribution(),
        "stage": stage_attribution(),
        "telemetry": telemetry_attribution(),
        "statistics": statistics_attribution(),
        "phases": phases_attribution(),
    }
    chaos = chaos_attribution()
    if chaos is not None:
        rec["chaos"] = chaos
    print(json.dumps(rec))


N_ORDERS = 1 << 19   # 512K orders
N_LINES = 1 << 21    # 2M lineitems


def build_q3_data():
    rng = np.random.default_rng(1)
    return {
        "o_orderkey": np.arange(N_ORDERS, dtype=np.int64),
        "o_flag": rng.integers(0, 10, N_ORDERS, dtype=np.int32),
        "l_orderkey": rng.integers(0, N_ORDERS, N_LINES, dtype=np.int64),
        "l_price": rng.random(N_LINES) * 1000.0,
        "l_disc": rng.random(N_LINES) * 0.1,
        "l_flag": rng.integers(0, 4, N_LINES, dtype=np.int32),
    }


def q3_oracle(d):
    keep_o = d["o_flag"] < 5
    keep_l = d["l_flag"] != 0
    okeys = d["o_orderkey"][keep_o]
    lkey = d["l_orderkey"][keep_l]
    rev = (d["l_price"] * (1.0 - d["l_disc"]))[keep_l]
    sel = np.isin(lkey, okeys)
    lkey, rev = lkey[sel], rev[sel]
    order = np.argsort(lkey, kind="stable")
    lkey, rev = lkey[order], rev[order]
    uk, starts = np.unique(lkey, return_index=True)
    sums = np.add.reduceat(rev, starts)
    top = np.argsort(-sums, kind="stable")[:10]
    return {int(uk[i]): float(sums[i]) for i in top}


def q3_bench():
    d = build_q3_data()
    q3_oracle(d)  # warm
    oracle, t_np = _median_time(lambda: q3_oracle(d))

    jax = init_backend()
    import jax.numpy as jnp

    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.columnar.column import Column, bucket_capacity
    from spark_rapids_tpu.exec.aggregate import AggregateExec
    from spark_rapids_tpu.exec.basic import (FilterExec, InMemoryScanExec,
                                             ProjectExec)
    from spark_rapids_tpu.exec.joins import HashJoinExec
    from spark_rapids_tpu.exec.sort import TopNExec
    from spark_rapids_tpu.expr.aggexprs import Sum
    from spark_rapids_tpu.expr.core import col, lit
    from spark_rapids_tpu.types import DOUBLE, INT, LONG, Schema, StructField

    o_schema = Schema((StructField("o_orderkey", LONG),
                       StructField("o_flag", INT)))
    l_schema = Schema((StructField("l_orderkey", LONG),
                       StructField("l_price", DOUBLE),
                       StructField("l_disc", DOUBLE),
                       StructField("l_flag", INT)))

    def mk_batch(schema, n):
        cap = bucket_capacity(n)
        cols = [Column.from_numpy(d[f.name], f.data_type, capacity=cap)
                for f in schema.fields]
        return ColumnarBatch(cols, n, schema)

    orders = mk_batch(o_schema, N_ORDERS)
    lines = mk_batch(l_schema, N_LINES)

    def make_q3_plan():
        o_scan = FilterExec(col("o_flag") < lit(5),
                            InMemoryScanExec([orders], o_schema))
        l_scan = FilterExec(col("l_flag") != lit(0),
                            InMemoryScanExec([lines], l_schema))
        joined = HashJoinExec(l_scan, o_scan, [col("l_orderkey")],
                              [col("o_orderkey")], "inner",
                              build_side="right")
        proj = ProjectExec([
            col("l_orderkey"),
            (col("l_price") * (lit(1.0) - col("l_disc"))).alias("rev")],
            joined)
        agg = AggregateExec([col("l_orderkey")],
                            [(Sum(col("rev")), "revenue")], proj)
        # the agg runs its EXACT tier (orderkey cardinality is far past
        # the speculative bucket table — speculating would trip every
        # iteration); the scope below exists for the JOIN's speculative
        # candidate sizing
        agg._spec_enabled = False
        # ISSUE 14: filter->probe->project->partial-agg fuses to one
        # program per stream batch (no-op under --stage-fusion off)
        return compile_lane_plan(TopNExec(10, [(col("revenue"), False)],
                                          agg))

    from spark_rapids_tpu.exec.speculation import speculation_scope
    from spark_rapids_tpu.exec.task_metrics import query_snapshot

    metrics_before = query_snapshot()

    @jax.jit
    def checksum(batch, prev, spec_flags):
        total = prev + batch.num_rows.astype(jnp.float64)
        for c in batch.columns:
            v = jnp.where(c.validity, c.data, jnp.zeros((), c.data.dtype))
            total = total + jnp.sum(v).astype(jnp.float64)
        for f in spec_flags:
            # a tripped join-sizing flag poisons the checksum: no invalid
            # iteration can pass the final assertion
            total = total + jnp.where(f, jnp.nan, 0.0)
        return total

    iters = 10

    def q3_lane(_i):
        plan = make_q3_plan()
        with speculation_scope() as scope:

            def run_once(prev):
                outs = list(plan.execute())
                flags = tuple(scope.drain())
                for b in outs:
                    prev = checksum(b, prev, flags)
                    flags = ()
                return outs, prev

            outs, chk = guarded_run(
                lambda: run_once(jnp.float64(0.0)))  # warm + verify
            rows = [r for b in outs for r in b.to_pylist()]
            got = {r[0]: r[1] for r in rows}
            assert set(got) == set(oracle), \
                (sorted(got)[:3], sorted(oracle)[:3])
            for k, v in oracle.items():
                assert abs(got[k] - v) / max(abs(v), 1) < 1e-9
            # second warm pass compiles the speculative (cached-bucket)
            # probe path
            _, chk2 = guarded_run(lambda: run_once(jnp.float64(0.0)))
            assert abs(float(np.asarray(chk2)) - float(np.asarray(chk))) \
                <= 1e-9 * max(abs(float(np.asarray(chk))), 1.0)
            expect1 = float(np.asarray(chk))

            t0 = time.perf_counter()
            chk = jnp.float64(0.0)
            for _ in range(iters):
                _, chk = guarded_run(lambda c=chk: run_once(c))
            final = float(np.asarray(chk))
            dt = (time.perf_counter() - t0) / iters
        assert abs(final - iters * expect1) <= 1e-9 * max(abs(final), 1.0)
        return plan, dt

    lanes = run_concurrent(q3_lane)
    plan, dt = lanes[0]
    if _CONCURRENCY > 1:
        # steady-state rate aggregate — see the q1 lane note
        dt = 1.0 / sum(1.0 / lane_dt for _plan, lane_dt in lanes)

    bytes_in = sum(v.nbytes for v in d.values())
    rec = {
        "metric": "q3_join_topn_throughput",
        "value": round(bytes_in / dt / 1e9, 3),
        "unit": "GB/s",
        "vs_baseline": round(t_np / dt, 3),
        "profile": query_attribution(plan, metrics_before),
        "pipeline": pipeline_attribution(),
        "lifecycle": lifecycle_attribution(),
        "workload": workload_attribution(),
        "gather": gather_attribution(),
        "shuffle": shuffle_attribution(),
        "ici": ici_attribution(),
        "upload": upload_attribution(),
        "encoded": encoded_attribution(),
        "dispatch": dispatch_attribution(),
        "adaptive": adaptive_attribution(),
        "speculation": speculation_attribution(),
        "stage": stage_attribution(),
        "telemetry": telemetry_attribution(),
        "statistics": statistics_attribution(),
        "phases": phases_attribution(),
    }
    chaos = chaos_attribution()
    if chaos is not None:
        rec["chaos"] = chaos
    print(json.dumps(rec))


if __name__ == "__main__":
    maybe_enable_event_log()
    maybe_enable_telemetry()
    maybe_enable_history()
    maybe_enable_faults()
    maybe_query_timeout()
    maybe_concurrency()
    maybe_stage_fusion()
    maybe_shuffle_mode()
    main()
    q3_bench()
