"""Headline benchmark: q1-style columnar aggregation throughput on one chip.

Runs the flagship pipeline (filter -> derived projection -> group-by
aggregate, the TPC-H q1 shape) through the exec layer on the default jax
backend (TPU under the driver; CPU elsewhere) and compares wall-clock
against a vectorized numpy oracle of the same query — a stand-in for the
CPU Spark columnar path until a real Spark harness is wired up.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import time

import numpy as np

ROWS = 1 << 22  # 4M rows
BATCHES = 4


def build_data():
    rng = np.random.default_rng(0)
    return {
        "returnflag": rng.integers(0, 4, ROWS, dtype=np.int32),
        "quantity": rng.integers(1, 51, ROWS, dtype=np.int64),
        "extendedprice": rng.random(ROWS) * 1000.0,
        "discount": rng.random(ROWS) * 0.1,
    }


def numpy_oracle(d):
    keep = d["quantity"] <= 45
    flag = d["returnflag"][keep]
    qty = d["quantity"][keep]
    dp = (d["extendedprice"] * (1.0 - d["discount"]))[keep]
    out = {}
    for k in np.unique(flag):
        m = flag == k
        out[int(k)] = (int(qty[m].sum()), float(dp[m].sum()), int(m.sum()))
    return out


def main():
    d = build_data()
    numpy_oracle(d)  # warm the page cache
    t_np0 = time.perf_counter()
    oracle = numpy_oracle(d)
    t_np = time.perf_counter() - t_np0

    import jax

    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.columnar.column import Column, bucket_capacity
    from spark_rapids_tpu.exec.aggregate import AggregateExec
    from spark_rapids_tpu.exec.basic import FilterExec, InMemoryScanExec, ProjectExec
    from spark_rapids_tpu.expr.aggexprs import Count, Sum
    from spark_rapids_tpu.expr.core import col, lit
    from spark_rapids_tpu.types import (
        DOUBLE, INT, LONG, Schema, StructField,
    )

    schema = Schema((
        StructField("returnflag", INT), StructField("quantity", LONG),
        StructField("extendedprice", DOUBLE), StructField("discount", DOUBLE),
    ))
    per = ROWS // BATCHES
    cap = bucket_capacity(per)
    batches = []
    for i in range(BATCHES):
        sl = slice(i * per, (i + 1) * per)
        cols = [Column.from_numpy(d[f.name][sl], f.data_type, capacity=cap)
                for f in schema.fields]
        batches.append(ColumnarBatch(cols, per, schema))

    def make_plan():
        scan = InMemoryScanExec(batches, schema)
        filt = FilterExec(col("quantity") <= lit(45), scan)
        proj = ProjectExec([
            col("returnflag"), col("quantity"),
            (col("extendedprice") * (lit(1.0) - col("discount")))
            .alias("disc_price")], filt)
        return AggregateExec(
            [col("returnflag")],
            [(Sum(col("quantity")), "sum_qty"),
             (Sum(col("disc_price")), "sum_disc"),
             (Count(), "cnt")], proj)

    # build ONCE: exec instances own their compiled kernels, so reuse across
    # iterations exercises the steady-state compiled path
    plan = make_plan()

    # warmup (compile)
    rows = plan.collect()
    got = {r[0]: (r[1], r[2], r[3]) for r in rows}
    for k, (sq, sd, c) in oracle.items():
        assert got[k][0] == sq and got[k][2] == c, (k, got[k], oracle[k])
        assert abs(got[k][1] - sd) / max(abs(sd), 1) < 1e-9

    iters = 5
    t0 = time.perf_counter()
    for _ in range(iters):
        out = plan.collect()
        assert len(out) == len(oracle)
    dt = (time.perf_counter() - t0) / iters

    bytes_in = sum(v.nbytes for v in d.values())
    gbps = bytes_in / dt / 1e9
    print(json.dumps({
        "metric": "q1_agg_throughput",
        "value": round(gbps, 3),
        "unit": "GB/s",
        "vs_baseline": round(t_np / dt, 3),
    }))


if __name__ == "__main__":
    main()
