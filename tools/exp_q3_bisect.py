"""Pipelined steady-state cost of each q3 sub-stage (one block at end).

Also prints the traceback of any num_rows_host call in steady state.
"""

import sys
import time
import traceback

sys.path.insert(0, "/root/repo")

import numpy as np
import jax
import jax.numpy as jnp

import bench
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import Column, bucket_capacity
from spark_rapids_tpu.exec.aggregate import AggregateExec
from spark_rapids_tpu.exec.basic import (FilterExec, InMemoryScanExec,
                                         ProjectExec)
from spark_rapids_tpu.exec.joins import HashJoinExec
from spark_rapids_tpu.exec.sort import TopNExec
from spark_rapids_tpu.expr.aggexprs import Sum
from spark_rapids_tpu.expr.core import col, lit
from spark_rapids_tpu.types import DOUBLE, INT, LONG, Schema, StructField
from spark_rapids_tpu.exec.speculation import speculation_scope

d = bench.build_q3_data()
o_schema = Schema((StructField("o_orderkey", LONG), StructField("o_flag", INT)))
l_schema = Schema((StructField("l_orderkey", LONG),
                   StructField("l_price", DOUBLE),
                   StructField("l_disc", DOUBLE),
                   StructField("l_flag", INT)))


def mk_batch(schema, n):
    cap = bucket_capacity(n)
    cols = [Column.from_numpy(d[f.name], f.data_type, capacity=cap)
            for f in schema.fields]
    return ColumnarBatch(cols, n, schema)


orders = mk_batch(o_schema, bench.N_ORDERS)
lines = mk_batch(l_schema, bench.N_LINES)

trace_nrh = "--trace-nrh" in sys.argv
if trace_nrh:
    orig = ColumnarBatch.num_rows_host

    def spy(self):
        traceback.print_stack(limit=8)
        return orig.fget(self)
    ColumnarBatch.num_rows_host = property(spy)


def mk_plan():
    o_scan = FilterExec(col("o_flag") < lit(5),
                        InMemoryScanExec([orders], o_schema))
    l_scan = FilterExec(col("l_flag") != lit(0),
                        InMemoryScanExec([lines], l_schema))
    joined = HashJoinExec(l_scan, o_scan, [col("l_orderkey")],
                          [col("o_orderkey")], "inner", build_side="right")
    proj = ProjectExec([
        col("l_orderkey"),
        (col("l_price") * (lit(1.0) - col("l_disc"))).alias("rev")], joined)
    agg = AggregateExec([col("l_orderkey")], [(Sum(col("rev")), "revenue")],
                        proj)
    agg._spec_enabled = False
    top = TopNExec(10, [(col("revenue"), False)], agg)
    return o_scan, l_scan, joined, proj, agg, top


o_scan, l_scan, joined, proj, agg, top = mk_plan()
cm = speculation_scope()
scope = cm.__enter__()


def steady(name, fn, iters=10):
    outs = fn()
    jax.block_until_ready([c.data for b in outs for c in b.columns])
    scope.drain()
    t0 = time.perf_counter()
    for _ in range(iters):
        outs = fn()
        scope.drain()
    jax.block_until_ready([c.data for b in outs for c in b.columns])
    dt = (time.perf_counter() - t0) / iters * 1e3
    print(f"{name:26s} {dt:9.1f} ms", flush=True)


which = [a for a in sys.argv[1:] if not a.startswith("--")] or \
    ["filters", "build", "counts", "probe", "join", "agg", "topn"]

if "filters" in which:
    steady("filters(l+o)", lambda: list(l_scan.execute())
           + list(o_scan.execute()))
if "build" in which:
    def run_build():
        b = list(o_scan.execute())[0]
        bt = joined._jit_build(b)
        return [b]
    steady("filters+build", run_build)
if "counts" in which:
    b0 = list(o_scan.execute())[0]
    bt0 = joined._jit_build(b0)

    def run_counts():
        lb = list(l_scan.execute())[0]
        joined._jit_counts(bt0, lb)
        return [lb]
    steady("filter(l)+counts", run_counts)
if "probe" in which:
    steady("join (full exec)", lambda: list(joined.execute()))
if "join" in which:
    steady("join+proj", lambda: list(proj.execute()))
if "agg" in which:
    steady("join+proj+agg", lambda: list(agg.execute()))
if "topn" in which:
    steady("full pipeline", lambda: list(top.execute()))
