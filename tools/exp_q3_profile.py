"""Per-stage profile of the q3 bench lane on the real chip (VERDICT r3
Weak #6: per-stage timers before optimizing blind)."""

import sys
import time

sys.path.insert(0, "/root/repo")

import numpy as np
import jax
import jax.numpy as jnp

import bench
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import Column, bucket_capacity
from spark_rapids_tpu.exec.aggregate import AggregateExec
from spark_rapids_tpu.exec.basic import FilterExec, InMemoryScanExec, ProjectExec
from spark_rapids_tpu.exec.joins import HashJoinExec
from spark_rapids_tpu.exec.sort import TopNExec
from spark_rapids_tpu.expr.aggexprs import Sum
from spark_rapids_tpu.expr.core import col, lit
from spark_rapids_tpu.types import DOUBLE, INT, LONG, Schema, StructField

d = bench.build_q3_data()
o_schema = Schema((StructField("o_orderkey", LONG), StructField("o_flag", INT)))
l_schema = Schema((StructField("l_orderkey", LONG),
                   StructField("l_price", DOUBLE),
                   StructField("l_disc", DOUBLE),
                   StructField("l_flag", INT)))


def mk_batch(schema, n):
    cap = bucket_capacity(n)
    cols = [Column.from_numpy(d[f.name], f.data_type, capacity=cap)
            for f in schema.fields]
    return ColumnarBatch(cols, n, schema)


orders = mk_batch(o_schema, bench.N_ORDERS)
lines = mk_batch(l_schema, bench.N_LINES)


def block_batches(bs):
    for b in bs:
        for c in b.columns:
            jax.block_until_ready(jax.tree_util.tree_leaves(c))
    return bs


def mk(upto):
    o_scan = FilterExec(col("o_flag") < lit(5),
                        InMemoryScanExec([orders], o_schema))
    l_scan = FilterExec(col("l_flag") != lit(0),
                        InMemoryScanExec([lines], l_schema))
    if upto == "scan":
        return l_scan
    joined = HashJoinExec(l_scan, o_scan, [col("l_orderkey")],
                          [col("o_orderkey")], "inner", build_side="right")
    if upto == "join":
        return joined
    proj = ProjectExec([
        col("l_orderkey"),
        (col("l_price") * (lit(1.0) - col("l_disc"))).alias("rev")], joined)
    if upto == "proj":
        return proj
    agg = AggregateExec([col("l_orderkey")], [(Sum(col("rev")), "revenue")],
                        proj)
    if upto == "agg":
        return agg
    return TopNExec(10, [(col("revenue"), False)], agg)


stages = sys.argv[1:] or ("scan", "join", "proj", "agg", "topn")
for upto in stages:
    plan = mk(upto)
    block_batches(list(plan.execute()))  # warm
    t0 = time.perf_counter()
    N = 3
    for _ in range(N):
        block_batches(list(plan.execute()))
    dt = (time.perf_counter() - t0) / N * 1e3
    print(f"{upto:6s} cumulative {dt:9.1f} ms")
