"""Measure v5e lax.sort cost vs lane count, and the packed-gather /
scatter alternatives, with the forced-checksum timing pattern
(block_until_ready is NOT trustworthy under axon — see exp_q3_stages)."""

import sys
import time

sys.path.insert(0, "/root/repo")

import numpy as np
import jax
import jax.numpy as jnp

N = 1 << 21
rng = np.random.default_rng(0)
keys = jnp.asarray(rng.integers(0, 1 << 31, N, dtype=np.uint32))
iota = jnp.arange(N, dtype=jnp.int32)
mat8 = jnp.asarray(rng.integers(0, 1 << 31, (N, 8), dtype=np.uint32))
mat16 = jnp.asarray(rng.integers(0, 1 << 31, (N, 16), dtype=np.uint32))
perm = jnp.asarray(rng.permutation(N).astype(np.int32))


def timed(name, fn, iters=6):
    out = fn(jnp.uint32(0))
    float(np.asarray(out))  # force
    t0 = time.perf_counter()
    chk = jnp.uint32(0)
    for _ in range(iters):
        chk = fn(chk)
    float(np.asarray(chk))
    dt = (time.perf_counter() - t0) / iters * 1e3
    print(f"{name:34s} {dt:8.1f} ms", flush=True)


def sort_l(lanes):
    @jax.jit
    def f(salt):
        ops = [keys ^ salt] + [keys] * (lanes - 1) + [iota]
        out = jax.lax.sort(tuple(ops), num_keys=lanes, is_stable=True)
        return out[-1][0].astype(jnp.uint32)
    return f


for L in (1, 2, 3, 4, 6, 8):
    timed(f"lax.sort {L} u32 key lanes + iota", sort_l(L))


@jax.jit
def sort_nokey_payload8(salt):
    # 1 key lane, 8 payload lanes carried through the sort
    ops = [keys ^ salt] + [mat8[:, j] for j in range(8)] + [iota]
    out = jax.lax.sort(tuple(ops), num_keys=1, is_stable=True)
    return out[-1][0].astype(jnp.uint32)


timed("sort 1 key + 8 payload lanes", sort_nokey_payload8)


@jax.jit
def gather_mat8(salt):
    g = mat8[perm ^ (salt & 0)]
    return g[0, 0] + salt


@jax.jit
def gather_mat16(salt):
    g = mat16[perm ^ (salt & 0)]
    return g[0, 0] + salt


timed("row gather (N,8) u32", gather_mat8)
timed("row gather (N,16) u32", gather_mat16)


@jax.jit
def scatter_mat8(salt):
    out = jnp.zeros((N, 8), jnp.uint32).at[perm].set(mat8)
    return out[0, 0] + salt


timed("row scatter .at[].set (N,8)", scatter_mat8)


@jax.jit
def packed_flag_sort(salt):
    # compaction-order candidate: single fused lane (flag<<31 | iota)
    flag = (keys ^ salt) >> jnp.uint32(31)
    word = (flag << jnp.uint32(31)) | iota.astype(jnp.uint32)
    out = jax.lax.sort((word,), num_keys=1, is_stable=False)
    return out[0][0]


timed("compaction: fused flag|iota 1-lane", packed_flag_sort)


@jax.jit
def two_lane_compaction(salt):
    flag = (keys ^ salt) >> jnp.uint32(31)
    out = jax.lax.sort((flag, iota), num_keys=1, is_stable=True)
    return out[1][0].astype(jnp.uint32)


timed("compaction: flag + iota 2-lane", two_lane_compaction)


@jax.jit
def cumsum_scatter_compact(salt):
    keep = ((keys ^ salt) >> jnp.uint32(31)) == 0
    dest = jnp.cumsum(keep.astype(jnp.int32)) - 1
    dest = jnp.where(keep, dest, N)
    out = jnp.zeros((N, 8), jnp.uint32).at[dest].set(mat8, mode="drop")
    return out[0, 0] + salt


timed("compaction: cumsum + row scatter", cumsum_scatter_compact)
