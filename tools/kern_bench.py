"""Per-kernel microbenchmark harness driving the fused-tier selection
(ISSUE 1: the tier choice is a measurement, not a guess).

For each kernel family and shape bucket, times the XLA formulation
against the fused Pallas kernel and records both. The records file
(tools/kern_bench.json by default) is what
`spark.rapids.tpu.pallas.fusedTier=auto` consults at trace time
(spark_rapids_tpu/ops/pallas_tier.py): a family only replaces its XLA
tier for a shape bucket where its recorded time wins.

Timing methodology (docs/perf.md round 5): `block_until_ready` returns
early under the axon tunnel, so each lane chains every iteration's
output into a device checksum scalar and the clock stops on the ONE
device->host fetch of the final checksum. Median of --reps timed runs.

Off-TPU the Pallas lanes run under the interpreter — they will lose by
orders of magnitude, which is precisely the point: `auto` then keeps the
XLA tier on CPU while a TPU round's records can flip it per shape.

Usage:
  python tools/kern_bench.py                          # default shapes
  python tools/kern_bench.py --families join_probe --shapes 4096x1024
  python tools/kern_bench.py --out tools/kern_bench.json --iters 20

Prints one JSON line per (family, shape) stage:
  {"family", "shape", "platform", "xla_ms", "pallas_ms", "winner"}
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

DEFAULT_SHAPES = {
    "join_probe": [(1 << 12, 1 << 10), (1 << 14, 1 << 12)],
    "scan_agg": [(1 << 14,), (1 << 16,)],
    "murmur3": [(1 << 16,), (1 << 20,)],
    # (gathered rows, source capacity) — the packed-row gather shapes
    # the join emit and filter compaction actually dispatch
    "gather": [(1 << 14, 1 << 12), (1 << 16, 1 << 14)],
    # (rows, n_partitions) — the device shuffle split pipeline: counts
    # + stable permutation + one partition-ordered packed row gather
    "partition_split": [(1 << 14, 8), (1 << 16, 32)],
    # (rows, n_columns) — packed one-copy host->device batch upload vs
    # the per-buffer jnp.asarray lane (ISSUE 10; lanes, not kernels)
    "h2d_upload": [(1 << 14, 8), (1 << 16, 16)],
    # (rows per map batch, n_partitions) — device-resident all_to_all
    # exchange vs the host serialize/LZ4 round trip it replaces
    # (ISSUE 16; lanes, not kernels)
    "ici_all_to_all": [(1 << 13, 8), (1 << 15, 8)],
    # (rows, dictionary entries) — the encoded lane's code-indexed take
    # of a per-dictionary table (precomputed hashes / literal hit
    # masks; ISSUE 18)
    "dict_gather": [(1 << 16, 1 << 10), (1 << 20, 1 << 12)],
}

#: smallest per-family shape for --quick CI smoke (compile + one
#: timed rep; proves the harness and the record layout, not the chip)
QUICK_SHAPES = {
    "join_probe": [(1 << 10, 1 << 8)],
    "scan_agg": [(1 << 12,)],
    "murmur3": [(1 << 14,)],
    "gather": [(1 << 11, 1 << 10)],
    "partition_split": [(1 << 11, 4)],
    "h2d_upload": [(1 << 11, 4)],
    "ici_all_to_all": [(1 << 10, 4)],
    "dict_gather": [(1 << 11, 1 << 8)],
}


def _timed(step, iters: int, reps: int) -> float:
    """Median wall-clock (ms) of `reps` runs of `iters` chained steps;
    step(chk) -> chk must consume and return the device checksum so no
    iteration can be elided or left queued when the clock stops."""
    import jax.numpy as jnp
    chk = step(jnp.float64(0.0))  # warm: compile + one round trip
    float(np.asarray(chk))
    times = []
    for _ in range(reps):
        chk = jnp.float64(0.0)
        t0 = time.perf_counter()
        for _ in range(iters):
            chk = step(chk)
        float(np.asarray(chk))  # forces completion of all iterations
        times.append((time.perf_counter() - t0) / iters * 1e3)
    return sorted(times)[len(times) // 2]


def bench_join_probe(shape, iters, reps, interpret):
    import jax
    import jax.numpy as jnp

    from spark_rapids_tpu.columnar.column import Column, bucket_capacity
    from spark_rapids_tpu.ops.join import (
        BuildTable, expand_candidates, int_key_lanes, probe_counts,
        verify_pairs)
    from spark_rapids_tpu.ops.pallas_join import fused_probe_verify
    from spark_rapids_tpu.types import LONG

    ns, nb = shape
    rng = np.random.default_rng(0)
    bk = Column.from_numpy(rng.integers(0, nb, nb).astype(np.int64),
                           LONG, capacity=bucket_capacity(nb))
    sk = Column.from_numpy(rng.integers(0, nb, ns).astype(np.int64),
                           LONG, capacity=bucket_capacity(ns))
    build = BuildTable.build([bk], [bk], jnp.int32(nb), bk.capacity)
    lo, counts, _ = probe_counts(build, [sk], jnp.int32(ns), sk.capacity)
    cand_cap = bucket_capacity(max(int(jnp.sum(counts)), 1))
    bk_lanes, bvalid = build.key_lanes
    sk_lanes, svalid = int_key_lanes([sk])

    @jax.jit
    def xla_step(chk):
        s_idx, b_pos, _ = expand_candidates(lo, counts, cand_cap)
        pv = s_idx >= 0
        ver, b_row = verify_pairs(build, [sk],
                                  jnp.where(pv, s_idx, -1),
                                  jnp.where(pv, b_pos, -1), pv)
        return chk + jnp.sum(ver).astype(jnp.float64) \
            + jnp.sum(b_row).astype(jnp.float64)

    @jax.jit
    def pallas_step(chk):
        ver, s_idx, b_pos, b_row = fused_probe_verify(
            lo, counts, bk_lanes, bvalid, sk_lanes, svalid, build.perm,
            cand_cap, interpret=interpret)
        return chk + jnp.sum(ver).astype(jnp.float64) \
            + jnp.sum(b_row).astype(jnp.float64)

    return (_timed(xla_step, iters, reps),
            _timed(pallas_step, iters, reps))


def bench_scan_agg(shape, iters, reps, interpret, G=32, n_keys=24):
    """XLA lane = the engine's masked tier at its DEFAULT configuration
    (32 slots x 2 rounds, exec/aggregate.py), Pallas lane = the fused
    kernel exactly as AggregateExec._streaming_step calls it (G =
    min(32, slots), single round) — a recorded 'win' must reflect the
    real substitution, not a toy baseline. n_keys=24 keeps the bucket
    table realistically loaded (clean but not trivially sparse); note
    the auto tier keys records by SHAPE bucket only, so record with
    data whose cardinality resembles the production workload."""
    import jax
    import jax.numpy as jnp

    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.columnar.column import Column, bucket_capacity
    from spark_rapids_tpu.expr.core import BoundReference
    from spark_rapids_tpu.ops.maskedagg import masked_groupby
    from spark_rapids_tpu.ops.pallas_fused import (
        compile_scan_agg_spec, fused_scan_agg_update)
    from spark_rapids_tpu.types import DOUBLE, LONG, Schema, StructField

    (n,) = shape
    rng = np.random.default_rng(1)
    key = Column.from_numpy(rng.integers(0, n_keys, n).astype(np.int64),
                            LONG, capacity=bucket_capacity(n))
    val = Column.from_numpy(rng.random(n) * 100, DOUBLE,
                            capacity=bucket_capacity(n))
    schema = Schema((StructField("k", LONG), StructField("v", DOUBLE)))
    batch = ColumnarBatch([key, val], n, schema)
    pre = [BoundReference(0, LONG, "k"), BoundReference(1, DOUBLE, "v")]
    agg_ops = [("sum", 1), ("count", 1), ("min", 1), ("max", 1)]
    spec = compile_scan_agg_spec([], pre, schema, 1, agg_ops, schema)
    assert spec is not None
    out_cap = bucket_capacity(G)

    def fold(chk, keys, results):
        for c in keys:
            chk = chk + jnp.sum(jnp.where(c.validity, c.data, 0)) \
                .astype(jnp.float64)
        for _, (d, v) in results:
            chk = chk + jnp.sum(jnp.where(v, d, jnp.zeros((), d.dtype))) \
                .astype(jnp.float64)
        return chk

    @jax.jit
    def xla_step(chk):
        # the engine's masked tier at its DEFAULT slots x rounds
        keys, results, ng, left = masked_groupby(
            [key], [(op, [key, val][s]) for op, s in agg_ops],
            batch.num_rows, batch.capacity, None, group_slots=32,
            rounds=2)
        return fold(chk, keys, results) + left

    @jax.jit
    def pallas_step(chk):
        keys, results, ng, left = fused_scan_agg_update(
            spec, batch, G, out_cap, interpret=interpret)
        return fold(chk, keys, results) + left

    return (_timed(xla_step, iters, reps),
            _timed(pallas_step, iters, reps))


def bench_murmur3(shape, iters, reps, interpret):
    import jax
    import jax.numpy as jnp

    from spark_rapids_tpu.ops import hashing as H
    from spark_rapids_tpu.ops.pallas_kernels import murmur3_long_lanes

    (n,) = shape
    rng = np.random.default_rng(2)
    data = jnp.asarray(rng.integers(-(2**62), 2**62, n), jnp.int64)
    seeds = jnp.full((n,), jnp.uint32(42))

    @jax.jit
    def xla_step(chk):
        return chk + jnp.sum(H.murmur3_long(data, seeds)
                             .astype(jnp.float64))

    @jax.jit
    def pallas_step(chk):
        return chk + jnp.sum(
            murmur3_long_lanes(data, seeds, interpret=interpret)
            .astype(jnp.float64))

    return (_timed(xla_step, iters, reps),
            _timed(pallas_step, iters, reps))


def bench_gather(shape, iters, reps, interpret):
    """Packed row gather (ISSUE 8): XLA's one-row-gather-over-the-pack
    formulation (ops/rowpack.gather_rows — the engine's floor) vs the
    DMA kernel (ops/pallas_gather.py), over a representative payload
    mix (1 LONG + 4 INT + 1 DOUBLE + 1 BOOLEAN = 9 u32 lanes incl the
    validity lane)."""
    import jax
    import jax.numpy as jnp

    from spark_rapids_tpu.columnar.column import Column, bucket_capacity
    from spark_rapids_tpu.ops.pallas_gather import pallas_gather_rows
    from spark_rapids_tpu.ops.rowpack import gather_rows, pack_rows
    from spark_rapids_tpu.types import BOOLEAN, DOUBLE, INT, LONG

    nout, cap = shape
    rng = np.random.default_rng(3)
    ccap = bucket_capacity(cap)
    cols = [Column.from_numpy(
        rng.integers(-(2**40), 2**40, cap).astype(np.int64), LONG,
        capacity=ccap)]
    for i in range(4):
        cols.append(Column.from_numpy(
            rng.integers(-1000, 1000, cap).astype(np.int32), INT,
            capacity=ccap))
    cols.append(Column.from_numpy(rng.random(cap), DOUBLE, capacity=ccap))
    cols.append(Column.from_numpy(rng.integers(0, 2, cap).astype(bool),
                                  BOOLEAN, capacity=ccap))
    plan, imat, fmat = pack_rows(cols)
    idx = jnp.asarray(rng.integers(0, cap, nout), jnp.int32)

    def fold(chk, gi, gf):
        chk = chk + jnp.sum(gi, dtype=jnp.float64)
        if gf is not None:
            chk = chk + jnp.sum(gf).astype(jnp.float64)
        return chk

    @jax.jit
    def xla_step(chk):
        gi, gf = gather_rows(plan, imat, fmat, idx)
        return fold(chk, gi, gf)

    @jax.jit
    def pallas_step(chk):
        gi, gf = pallas_gather_rows(plan, imat, fmat, idx,
                                    interpret=interpret)
        return fold(chk, gi, gf)

    return (_timed(xla_step, iters, reps),
            _timed(pallas_step, iters, reps))


def bench_partition_split(shape, iters, reps, interpret):
    """Device shuffle partition split (ISSUE 9): segment-sum counts +
    stable sort-by-pid permutation + ONE partition-ordered packed row
    gather over the 9-lane payload mix — the exact pipeline
    `HostShuffleExchangeExec`'s device lane dispatches per written
    batch. XLA lane serves the gather from ops/rowpack (the floor),
    Pallas lane from the DMA kernel; the counts/permutation prefix is
    shared, so the delta isolates the tiered step."""
    import jax
    import jax.numpy as jnp

    from spark_rapids_tpu.columnar.column import Column, bucket_capacity
    from spark_rapids_tpu.ops.pallas_gather import pallas_gather_rows
    from spark_rapids_tpu.ops.partition_split import partition_table
    from spark_rapids_tpu.ops.rowpack import gather_rows, pack_rows
    from spark_rapids_tpu.types import BOOLEAN, DOUBLE, INT, LONG

    rows, n_parts = shape
    rng = np.random.default_rng(4)
    cap = bucket_capacity(rows)
    cols = [Column.from_numpy(
        rng.integers(-(2**40), 2**40, rows).astype(np.int64), LONG,
        capacity=cap)]
    for _ in range(4):
        cols.append(Column.from_numpy(
            rng.integers(-1000, 1000, rows).astype(np.int32), INT,
            capacity=cap))
    cols.append(Column.from_numpy(rng.random(rows), DOUBLE, capacity=cap))
    cols.append(Column.from_numpy(rng.integers(0, 2, rows).astype(bool),
                                  BOOLEAN, capacity=cap))
    plan, imat, fmat = pack_rows(cols)
    pid = jnp.asarray(rng.integers(0, n_parts, cap), jnp.int32)
    num_rows = jnp.int32(rows)

    def split(gather_fn):
        counts, order = partition_table(pid, num_rows, cap, n_parts)
        gi, gf = gather_fn(plan, imat, fmat, order)
        chk = jnp.sum(counts).astype(jnp.float64) \
            + jnp.sum(gi, dtype=jnp.float64)
        if gf is not None:
            chk = chk + jnp.sum(gf).astype(jnp.float64)
        return chk

    @jax.jit
    def xla_step(chk):
        return chk + split(gather_rows)

    @jax.jit
    def pallas_step(chk):
        return chk + split(
            lambda p, i, f, idx: pallas_gather_rows(
                p, i, f, idx, interpret=interpret))

    return (_timed(xla_step, iters, reps),
            _timed(pallas_step, iters, reps))


def bench_h2d_upload(shape, iters, reps, interpret):
    """Packed one-copy host->device upload (columnar/upload.py: pool
    staging pack + ONE device_put + jitted device unpack) vs the
    per-buffer lane (one jnp.asarray per data/validity buffer). The
    record's two slots map lanes, not kernels: xla_ms = per-buffer,
    pallas_ms = packed. `interpret` is unused — neither lane is a
    Pallas kernel; the runtime gate is
    spark.rapids.tpu.transfer.packedUpload.enabled, and a TPU round
    reads this family to quantify the one-copy win per rows x cols
    bucket."""
    import jax
    import jax.numpy as jnp

    from spark_rapids_tpu.columnar.column import (Column, bucket_capacity,
                                                  host_build)
    from spark_rapids_tpu.columnar.upload import packed_upload_batch
    from spark_rapids_tpu.types import (BOOLEAN, DOUBLE, INT, LONG, Schema,
                                        StructField)

    rows, n_cols = shape
    rng = np.random.default_rng(7)
    cap = bucket_capacity(rows)
    dtypes = [LONG, INT, DOUBLE, BOOLEAN]
    fields, cols = [], []
    with host_build():
        for c in range(n_cols):
            dt = dtypes[c % len(dtypes)]
            if dt is LONG:
                vals = rng.integers(-(2**40), 2**40, rows).astype(np.int64)
            elif dt is INT:
                vals = rng.integers(-1000, 1000, rows).astype(np.int32)
            elif dt is DOUBLE:
                vals = rng.random(rows)
            else:
                vals = rng.integers(0, 2, rows).astype(bool)
            valid = rng.random(rows) > 0.1
            cols.append(Column.from_numpy(vals, dt, valid, capacity=cap))
            fields.append(StructField(f"c{c}", dt))
    schema = Schema(tuple(fields))
    host_leaves = jax.tree_util.tree_flatten(cols)[0]

    @jax.jit
    def _chk(leaves, chk):
        for x in leaves:
            chk = chk + jnp.sum(x.astype(jnp.float64))
        return chk

    def per_buffer_step(chk):
        dev = [jnp.asarray(a) for a in host_leaves]
        return _chk(dev, chk)

    def packed_step(chk):
        batch = packed_upload_batch(cols, rows, schema)
        return _chk(jax.tree_util.tree_leaves(list(batch.columns)), chk)

    return (_timed(per_buffer_step, iters, reps),
            _timed(packed_step, iters, reps))


def bench_ici_all_to_all(shape, iters, reps, interpret):
    """ICI-native device-resident shuffle exchange (ISSUE 16). The
    record's two slots map lanes, not kernels: xla_ms = the host
    fallback lane's per-map-batch serialize/LZ4 -> deserialize/upload
    round trip (shuffle/serializer.py), pallas_ms = the packed device
    all_to_all exchange step (parallel/exchange.exchange_columns under
    shard_map). `interpret` is unused — neither lane is a Pallas
    kernel; the runtime gate is spark.rapids.tpu.shuffle.ici.enabled.
    Shape is (rows per map batch, n_partitions); the mesh spans
    min(n_partitions, visible devices) so the family records on a
    single-device host too (there the collective degenerates to a local
    permutation — a TPU pod round is what makes the record
    meaningful)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.columnar.column import Column, bucket_capacity
    from spark_rapids_tpu.parallel.distributed import stack_batches
    from spark_rapids_tpu.parallel.exchange import (exchange_columns,
                                                    negotiate_slot_cap)
    from spark_rapids_tpu.parallel.mesh import (DATA_AXIS, device_mesh,
                                                shard_map_compat)
    from spark_rapids_tpu.shuffle.serializer import (deserialize_batch,
                                                     serialize_batch)
    from spark_rapids_tpu.types import LONG, Schema, StructField

    rows, n_parts = shape
    n = min(n_parts, len(jax.devices()))
    mesh = device_mesh(n)
    rng = np.random.default_rng(16)
    cap = bucket_capacity(rows)
    schema = Schema((StructField("k", LONG), StructField("v", LONG)))
    batches = []
    for _ in range(n):
        k = Column.from_numpy(
            rng.integers(0, 1 << 20, rows).astype(np.int64), LONG,
            capacity=cap)
        v = Column.from_numpy(
            rng.integers(-(2**40), 2**40, rows).astype(np.int64), LONG,
            capacity=cap)
        batches.append(ColumnarBatch([k, v], rows, schema))
    stacked = stack_batches(batches)
    # worst-case-safe slot cap (one device could hash every row to one
    # partition); production rounds negotiate a measured cap instead
    slot_cap = negotiate_slot_cap(rows, cap)

    def spmd(st):
        local = jax.tree_util.tree_map(lambda x: x[0], st)
        cols, n_recv = exchange_columns(
            list(local.columns), (0,), local.num_rows, local.capacity,
            DATA_AXIS, n, slot_cap=slot_cap)
        return jax.tree_util.tree_map(
            lambda x: x[None], ColumnarBatch(cols, n_recv, schema))

    step = jax.jit(shard_map_compat(
        spmd, mesh=mesh, in_specs=P(DATA_AXIS), out_specs=P(DATA_AXIS)))

    def fold(chk, batch):
        for c in batch.columns:
            chk = chk + jnp.sum(jnp.where(c.validity, c.data, 0)) \
                .astype(jnp.float64)
        return chk

    def host_step(chk):
        for b in batches:
            chk = fold(chk, deserialize_batch(serialize_batch(b), schema))
        return chk

    def ici_step(chk):
        return fold(chk, step(stacked))

    return (_timed(host_step, iters, reps),
            _timed(ici_step, iters, reps))


def bench_dict_gather(shape, iters, reps, interpret):
    """Code-indexed take over a per-dictionary lookup table (ISSUE 18):
    the encoded lane's dict_take (columnar/encoded.py) — precomputed
    join hashes, literal hit masks and late materialization all index a
    small table by the i32 code lane. xla_ms = the `table[clip(codes)]`
    take; pallas_ms = the DMA row gather (ops/pallas_gather.py) over
    the table as a one-lane matrix, exactly the tier dict_take selects
    between. Shape is (rows, dictionary entries)."""
    import jax
    import jax.numpy as jnp

    from spark_rapids_tpu.ops.pallas_gather import dma_row_gather

    rows, n = shape
    rng = np.random.default_rng(18)
    table = jnp.asarray(
        rng.integers(0, 1 << 32, n, dtype=np.uint64).astype(np.uint32))
    codes = jnp.asarray(rng.integers(0, n, rows), jnp.int32)
    mat = table.reshape(n, 1)

    @jax.jit
    def xla_step(chk):
        out = table[jnp.clip(codes, 0, n - 1)]
        return chk + jnp.sum(out, dtype=jnp.float64)

    def pallas_step(chk):
        out = dma_row_gather(mat, codes, interpret=interpret)[:, 0]
        return chk + jnp.sum(out, dtype=jnp.float64)

    return (_timed(xla_step, iters, reps),
            _timed(jax.jit(pallas_step), iters, reps))


BENCHES = {
    "join_probe": bench_join_probe,
    "scan_agg": bench_scan_agg,
    "murmur3": bench_murmur3,
    "gather": bench_gather,
    "partition_split": bench_partition_split,
    "h2d_upload": bench_h2d_upload,
    "ici_all_to_all": bench_ici_all_to_all,
    "dict_gather": bench_dict_gather,
}


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--families", nargs="*", default=list(BENCHES),
                    choices=list(BENCHES))
    ap.add_argument("--shapes", nargs="*", default=None,
                    help="override shapes, e.g. 4096x1024 (join) or "
                         "65536 (1-D families)")
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--quick", action="store_true",
                    help="CI smoke: one tiny shape per family, 2 iters "
                         "x 1 rep — proves the harness + record layout "
                         "end to end, not the chip")
    ap.add_argument("--out", default=None,
                    help="records file (default tools/kern_bench.json)")
    ap.add_argument("--dry-run", action="store_true",
                    help="measure and print, do not write the record "
                         "file")
    args = ap.parse_args(argv)
    if args.quick:
        args.iters = min(args.iters, 2)
        args.reps = 1
        if args.out is None and not args.dry_run:
            # a 1-rep tiny-shape smoke record is NOISE, not a
            # measurement — never let it land in the production file
            # the auto tier trusts
            ap.error("--quick writes throwaway records; pass an "
                     "explicit --out (not the production "
                     "kern_bench.json) or --dry-run")
    if args.out is None:
        args.out = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "kern_bench.json")

    import jax

    from spark_rapids_tpu.ops.pallas_kernels import on_tpu
    from spark_rapids_tpu.ops.pallas_tier import (
        KERN_BENCH_SCHEMA, shape_bucket)

    platform = jax.default_backend()
    interpret = not on_tpu()

    # merge with existing records so shape coverage accumulates — but
    # only records of the CURRENT layout; a stale-schema file is
    # discarded loudly (the tier selector already refuses to read it)
    doc = {"schema": KERN_BENCH_SCHEMA, "records": []}
    if os.path.exists(args.out) and not args.dry_run:
        try:
            with open(args.out) as f:
                old = json.load(f)
            if old.get("schema") == KERN_BENCH_SCHEMA:
                doc = old
            else:
                print(json.dumps({
                    "discarded_stale_records": args.out,
                    "old_schema": old.get("schema"),
                    "schema": KERN_BENCH_SCHEMA}))
        except (OSError, ValueError):
            pass
    index = {(r["family"], r["platform"], tuple(r["shape_bucket"])): r
             for r in doc.get("records", ())}

    if args.shapes and len(args.families) != 1:
        ap.error("--shapes overrides one family's shape list; pass "
                 "exactly one --families with it (families differ in "
                 "shape arity)")

    for family in args.families:
        shapes = (QUICK_SHAPES if args.quick else DEFAULT_SHAPES)[family]
        if args.shapes:
            shapes = [tuple(int(x) for x in s.split("x"))
                      for s in args.shapes]
            arity = len(DEFAULT_SHAPES[family][0])
            bad = [s for s in shapes if len(s) != arity]
            if bad:
                ap.error(f"{family} shapes need {arity} dims "
                         f"(got {bad})")
        for shape in shapes:
            xla_ms, pallas_ms = BENCHES[family](
                shape, args.iters, args.reps, interpret)
            rec = {
                "schema": KERN_BENCH_SCHEMA,
                "family": family,
                "platform": platform,
                "shape": list(shape),
                "shape_bucket": list(shape_bucket(shape)),
                "xla_ms": round(xla_ms, 4),
                "pallas_ms": round(pallas_ms, 4),
                "winner": "pallas" if pallas_ms < xla_ms else "xla",
                "iters": args.iters,
                "interpret": interpret,
                "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
            }
            index[(family, platform, tuple(rec["shape_bucket"]))] = rec
            print(json.dumps({k: rec[k] for k in (
                "family", "shape", "platform", "xla_ms", "pallas_ms",
                "winner")}))

    if not args.dry_run:
        doc["schema"] = KERN_BENCH_SCHEMA
        doc["records"] = list(index.values())
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=1)
        print(json.dumps({"written": args.out, "schema": KERN_BENCH_SCHEMA,
                          "records": len(doc["records"])}))


if __name__ == "__main__":
    main()
