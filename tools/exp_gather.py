"""Measure gather formulations on the real chip (round-4 join unlock).

Each variant is timed steady-state: an int32 device carry chains iterations
(no elision), ONE d2h fetch at the end. Per-program launch via the tunnel is
~1-4.5 ms, so fast variants use more iters.
"""

import time
import sys

import numpy as np

import jax
import jax.numpy as jnp

M = 1 << 19   # table rows (q3 build side)
N = 1 << 21   # queries (q3 stream side)


def timeit(name, fn, iters=8):
    c = jnp.int32(0)
    c = fn(c)  # warm/compile
    c.block_until_ready()
    t0 = time.perf_counter()
    c = jnp.int32(0)
    for _ in range(iters):
        c = fn(c)
    int(c)  # one fetch
    dt = (time.perf_counter() - t0) / iters * 1e3
    print(f"{name:34s} {dt:9.2f} ms")
    return dt


def main():
    rng = np.random.default_rng(0)
    idx = jnp.asarray(rng.integers(0, M, N, dtype=np.int32))
    t_i32 = jnp.asarray(rng.integers(0, 1 << 30, M, dtype=np.int32))
    t_i64 = t_i32.astype(jnp.int64)
    t_r8 = jnp.asarray(rng.integers(0, 1 << 30, (M, 8), dtype=np.int32))
    t_r128 = jnp.asarray(
        rng.integers(0, 1 << 30, (M, 128), dtype=np.int32))
    sidx = jnp.sort(idx)
    perm = jnp.asarray(rng.permutation(N).astype(np.int32))
    vals = jnp.asarray(rng.integers(0, 1 << 30, N, dtype=np.int32))
    key32 = jnp.asarray(rng.integers(0, 1 << 31, N, dtype=np.uint32))

    which = sys.argv[1:] if len(sys.argv) > 1 else None

    def want(n):
        return which is None or any(w in n for w in which)

    if want("g_1col_i32"):
        @jax.jit
        def f(c):
            y = t_i32[(idx + (c & 1))]
            return c + y[0]
        timeit("g_1col_i32 (2M from 512K)", f, 4)

    if want("g_1col_i64"):
        @jax.jit
        def f(c):
            y = t_i64[(idx + (c & 1))]
            return c + y[0].astype(jnp.int32)
        timeit("g_1col_i64", f, 4)

    if want("g_row8"):
        @jax.jit
        def f(c):
            y = t_r8[(idx + (c & 1))]
            return c + y[0, 0]
        timeit("g_row8 (2M rows of 8xi32)", f, 4)

    if want("g_row128"):
        @jax.jit
        def g(c, t):
            y = t[(idx + (c & 1))]
            return c + y[0, 0]

        def f(c):
            return g(c, t_r128)
        timeit("g_row128 (2M rows of 128xi32)", f, 2)

    if want("g_row32"):
        t_r32 = t_r128[:, :32]

        @jax.jit
        def g(c, t):
            y = t[(idx + (c & 1))]
            return c + y[0, 0]

        def f(c):
            return g(c, t_r32)
        timeit("g_row32 (2M rows of 32xi32)", f, 4)

    if want("g_two_in_one"):
        @jax.jit
        def f(c):
            y = t_i32[(idx + (c & 1))]
            z = t_i32[(idx ^ 1)]
            return c + y[0] + z[0]
        timeit("two 1col gathers in one program", f, 4)

    if want("g_sorted"):
        @jax.jit
        def f(c):
            y = jnp.take(t_i32, sidx + (c & 1), indices_are_sorted=True)
            return c + y[0]
        timeit("g_sorted_flag", f, 4)

    if want("taa"):
        # per-lane gather: table (4096,128), idx rows in [0,4096)
        tl = t_r128[:4096]
        il = (idx.reshape(-1, 128) % 4096)

        @jax.jit
        def f(c):
            y = jnp.take_along_axis(tl, (il + (c & 1)) % 4096, axis=0)
            return c + y[0, 0]
        timeit("taa_perlane XLA (16K,128)<-4096", f, 4)

    if want("scatter_set"):
        @jax.jit
        def f(c):
            z = jnp.zeros((N,), jnp.int32)
            z = z.at[perm].set(vals + (c & 1), mode="drop",
                               unique_indices=True)
            return c + z[0]
        timeit("scatter_set 2M unique", f, 4)

    if want("scatter_add"):
        @jax.jit
        def f(c):
            z = jnp.zeros((M,), jnp.int32)
            z = z.at[idx].add(vals + (c & 1), mode="drop")
            return c + z[0]
        timeit("scatter_add 2M->512K", f, 4)

    if want("sort2"):
        @jax.jit
        def f(c):
            k, v = jax.lax.sort((key32 + (c & 1).astype(jnp.uint32), vals),
                                num_keys=1)
            return c + v[0]
        timeit("sort 2M (u32 key + i32 payload)", f, 4)

    if want("sort3"):
        @jax.jit
        def f(c):
            k, v, w = jax.lax.sort(
                (key32 + (c & 1).astype(jnp.uint32), vals, perm), num_keys=1)
            return c + v[0]
        timeit("sort 2M (u32 + 2 payloads)", f, 4)

    if want("pallas_dg"):
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        R = 4096  # table rows per lane-block; table (R,128) = 2MB VMEM

        def kern(t_ref, i_ref, o_ref):
            o_ref[:] = jnp.take_along_axis(t_ref[:], i_ref[:], axis=0)

        tl = t_r128[:R]
        il = (idx.reshape(-1, 128) % R)
        S = il.shape[0]  # 16384
        BLK = R  # out block rows must equal table rows for the rule

        def dg(tbl, ii):
            with jax.enable_x64(False):
                return pl.pallas_call(
                    kern,
                    out_shape=jax.ShapeDtypeStruct((S, 128), jnp.int32),
                    grid=(S // BLK,),
                    in_specs=[
                        pl.BlockSpec((R, 128), lambda i: (0, 0),
                                     memory_space=pltpu.VMEM),
                        pl.BlockSpec((BLK, 128), lambda i: (i, 0),
                                     memory_space=pltpu.VMEM),
                    ],
                    out_specs=pl.BlockSpec((BLK, 128), lambda i: (i, 0),
                                           memory_space=pltpu.VMEM),
                )(tbl, ii)

        @jax.jit
        def f(c):
            y = dg(tl, (il + (c & 1)) % R)
            return c + y[0, 0]
        timeit("pallas dynamic_gather perlane 2M", f, 8)


if __name__ == "__main__":
    main()
