"""q3 per-stage steady-state costs, measured the only way the axon tunnel
can be trusted: each prefix of the pipeline runs ITERS chained iterations
whose outputs fold into a device checksum scalar, and the wall clock stops
only after np.asarray(checksum) lands on the host. (block_until_ready
under axon returns early — tools/exp_join_parts.py measured 0.09 ms for a
2M-row hash program, less than one tunnel RTT — so every number from the
old bisect/parts harnesses is dispatch time, not device time.)

Prints one line per prefix; the difference between consecutive prefixes is
the marginal steady-state cost of that stage.
"""

import sys
import time

sys.path.insert(0, "/root/repo")

import numpy as np
import jax
import jax.numpy as jnp

import bench
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import Column, bucket_capacity
from spark_rapids_tpu.exec.aggregate import AggregateExec
from spark_rapids_tpu.exec.basic import (FilterExec, InMemoryScanExec,
                                         ProjectExec)
from spark_rapids_tpu.exec.joins import HashJoinExec
from spark_rapids_tpu.exec.sort import TopNExec
from spark_rapids_tpu.exec.speculation import speculation_scope
from spark_rapids_tpu.expr.aggexprs import Sum
from spark_rapids_tpu.expr.core import col, lit
from spark_rapids_tpu.types import DOUBLE, INT, LONG, Schema, StructField

d = bench.build_q3_data()
o_schema = Schema((StructField("o_orderkey", LONG),
                   StructField("o_flag", INT)))
l_schema = Schema((StructField("l_orderkey", LONG),
                   StructField("l_price", DOUBLE),
                   StructField("l_disc", DOUBLE),
                   StructField("l_flag", INT)))


def mk_batch(schema, n):
    cap = bucket_capacity(n)
    cols = [Column.from_numpy(d[f.name], f.data_type, capacity=cap)
            for f in schema.fields]
    return ColumnarBatch(cols, n, schema)


orders = mk_batch(o_schema, bench.N_ORDERS)
lines = mk_batch(l_schema, bench.N_LINES)


def mk_stages():
    o_scan = FilterExec(col("o_flag") < lit(5),
                        InMemoryScanExec([orders], o_schema))
    l_scan = FilterExec(col("l_flag") != lit(0),
                        InMemoryScanExec([lines], l_schema))
    joined = HashJoinExec(l_scan, o_scan, [col("l_orderkey")],
                          [col("o_orderkey")], "inner", build_side="right")
    proj = ProjectExec([
        col("l_orderkey"),
        (col("l_price") * (lit(1.0) - col("l_disc"))).alias("rev")], joined)
    agg = AggregateExec([col("l_orderkey")], [(Sum(col("rev")), "revenue")],
                        proj)
    agg._spec_enabled = False
    top = TopNExec(10, [(col("revenue"), False)], agg)
    return [("filter_l", l_scan), ("filter_o", o_scan), ("join", joined),
            ("join+proj", proj), ("+agg", agg), ("+topn", top)]


@jax.jit
def checksum(batch, prev):
    total = prev + batch.num_rows.astype(jnp.float64)
    for c in batch.columns:
        if c.data is None:
            continue
        v = jnp.where(c.validity, c.data, jnp.zeros((), c.data.dtype))
        total = total + jnp.sum(v.astype(jnp.float64))
    return total


ITERS = int(sys.argv[1]) if len(sys.argv) > 1 else 6
REPS = int(sys.argv[2]) if len(sys.argv) > 2 else 2

stages = mk_stages()
results = {name: [] for name, _ in stages}

with speculation_scope() as scope:
    # warm every stage once (compile + populate size caches)
    for name, ex in stages:
        chk = jnp.float64(0.0)
        for b in ex.execute():
            chk = checksum(b, chk)
        scope.drain()
        float(np.asarray(chk))

    for rep in range(REPS):
        for name, ex in stages:
            t0 = time.perf_counter()
            chk = jnp.float64(0.0)
            for _ in range(ITERS):
                for b in ex.execute():
                    chk = checksum(b, chk)
                scope.drain()
            float(np.asarray(chk))  # ONE forced sync closes the clock
            dt = (time.perf_counter() - t0) / ITERS * 1e3
            results[name].append(dt)
            print(f"rep{rep} {name:12s} {dt:9.1f} ms", flush=True)

meds = {name: sorted(results[name])[len(results[name]) // 2]
        for name, _ in stages}
prefix = {"filter_l": 0.0, "filter_o": 0.0,
          "join": meds["filter_l"] + meds["filter_o"],
          "join+proj": meds["join"], "+agg": meds["join+proj"],
          "+topn": meds["+agg"]}
for name, _ in stages:
    med = meds[name]
    print(f"{name:12s} {med:9.1f} ms   (marginal +{med - prefix[name]:7.1f})"
          f"   runs={['%.1f' % x for x in results[name]]}", flush=True)
