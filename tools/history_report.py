"""Aggregate query-history capsules into a per-plan performance report,
diff two history dirs to rank regressions by the phase that moved, and
run the profiling advisor (ISSUE 17 tentpole part 3 — the reference's
qualification/profiling tool over Spark event logs, rebuilt over the
engine's own capsules).

Usage:
    python tools/history_report.py HISTORY_DIR [--top N]
                                   [--format text|json]
    python tools/history_report.py CUR_DIR --diff BASE_DIR

Each capsule is one JSONL line per finished governed query
(obs/history.py): plan fingerprint, the closed wall-clock phase ledger
(sum(phases) == wall_ns), essential metrics, worst exchange skew, and
the per-query deltas of the dispatch/shuffle/ici/upload/workload
process counters. Everything here joins on `fingerprint` — the
canonical plan identity — so two runs of the same workload compare
plan-by-plan without re-reading a single plan.

The advisor is a CLOSED rule registry (`ADVISOR_RULES`, lint-checked
against the docs/robustness.md advisor table like the fault-point and
event-kind registries): each rule looks at one per-fingerprint
aggregate, and fires with the evidence and the conf to turn. Rules
never guess — no evidence, no advice.

Stdlib only; importable (`read_capsules`, `aggregate`, `diff_report`,
`advise`) for tests and embedding.
"""

from __future__ import annotations

import argparse
import glob as _glob
import json
import os
import sys
from typing import Any, Callable, Dict, List, NamedTuple, Optional

#: the closed phase set, mirrored from obs/phase.PHASES (stdlib-only
#: tool: no engine import; tests/test_history_report.py asserts the two
#: stay identical)
PHASES = (
    "admission-wait", "compile", "device-compute", "host-pack-serialize",
    "shuffle-io", "ici-collective", "spill-wait", "semaphore-wait",
    "pipeline-stall", "retry-backoff", "spec-wait", "other",
)


# ---------------------------------------------------------------------------
# capsule ingestion
# ---------------------------------------------------------------------------

def read_capsules(directory: str) -> List[Dict[str, Any]]:
    """Every parseable capsule under `directory` (all processes, all
    rotated members), oldest-first by timestamp. Truncated final lines
    (a SIGKILL'd process) are skipped, like profile_report."""
    out: List[Dict[str, Any]] = []
    bad = 0
    for path in sorted(_glob.glob(os.path.join(directory, "history-*.jsonl"))):
        with open(path) as f:
            for ln in f:
                ln = ln.strip()
                if not ln:
                    continue
                try:
                    out.append(json.loads(ln))
                except ValueError:
                    bad += 1
    if bad:
        print(f"warning: skipped {bad} unparseable capsule line(s)",
              file=sys.stderr)
    out.sort(key=lambda c: c.get("ts_ms", 0))
    return out


def _pct(sorted_vals: List[int], pct: int) -> int:
    n = len(sorted_vals)
    if n == 0:
        return 0
    rank = max(1, -(-pct * n // 100))  # ceil, nearest-rank
    return sorted_vals[min(n, rank) - 1]


def _sum_family(agg: Dict[str, int], fam: Optional[Dict[str, Any]]) -> None:
    for k, v in (fam or {}).items():
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            agg[k] = agg.get(k, 0) + v


def aggregate(capsules: List[Dict[str, Any]]) -> Dict[str, Dict[str, Any]]:
    """Per-fingerprint roll-up: run count, wall p50/p95, per-phase mean
    ns, summed counter-family deltas, worst skew — the join table every
    other surface (report / diff / advisor) reads. Capsules without a
    fingerprint aggregate under "(none)"."""
    by_fp: Dict[str, Dict[str, Any]] = {}
    for c in capsules:
        fp = c.get("fingerprint") or "(none)"
        a = by_fp.get(fp)
        if a is None:
            a = by_fp[fp] = {
                "fingerprint": fp, "count": 0, "ok": 0, "walls": [],
                "phase_ns": {p: 0 for p in PHASES}, "phase_runs": 0,
                "rows": 0, "spill_bytes": 0, "mesh_devices": 1,
                "skew": None,
                "dispatch": {}, "shuffle": {}, "ici": {}, "upload": {},
                "workload": {}, "encoded": {}, "adaptive": {},
                "speculation": {},
            }
        a["count"] += 1
        a["ok"] += 1 if c.get("ok") else 0
        a["walls"].append(int(c.get("wall_ns", 0)))
        a["rows"] += c.get("rows", 0)
        a["spill_bytes"] += c.get("spill_bytes", 0)
        a["mesh_devices"] = max(a["mesh_devices"],
                                int(c.get("mesh_devices", 1)))
        ph = c.get("phases")
        if ph:
            a["phase_runs"] += 1
            for p in PHASES:
                a["phase_ns"][p] += int(ph.get(p, 0))
        sk = c.get("skew")
        if sk and (a["skew"] is None
                   or sk.get("ratio", 0) > a["skew"].get("ratio", 0)):
            a["skew"] = sk
        for fam in ("dispatch", "shuffle", "ici", "upload", "workload",
                    "encoded", "adaptive", "speculation"):
            _sum_family(a[fam], c.get(fam))
    for a in by_fp.values():
        walls = sorted(a.pop("walls"))
        a["p50_wall_ns"] = _pct(walls, 50)
        a["p95_wall_ns"] = _pct(walls, 95)
        runs = max(1, a["phase_runs"])
        a["phase_mean_ns"] = {p: v // runs
                              for p, v in a.pop("phase_ns").items()}
    return by_fp


# ---------------------------------------------------------------------------
# diff: rank regressions by the phase that moved
# ---------------------------------------------------------------------------

def diff_report(base: Dict[str, Dict[str, Any]],
                cur: Dict[str, Dict[str, Any]],
                ) -> List[Dict[str, Any]]:
    """Join two aggregates on fingerprint and rank by p50 wall-clock
    regression (worst first). Each row names the phase whose mean moved
    the most — the "WHERE did it get slower" answer --diff exists
    for. Improvements rank at the bottom with negative deltas."""
    rows: List[Dict[str, Any]] = []
    for fp, c in cur.items():
        b = base.get(fp)
        if b is None:
            continue
        delta = c["p50_wall_ns"] - b["p50_wall_ns"]
        phase_deltas = {
            p: c["phase_mean_ns"].get(p, 0) - b["phase_mean_ns"].get(p, 0)
            for p in PHASES}
        worst = max(phase_deltas, key=phase_deltas.__getitem__)
        rows.append({
            "fingerprint": fp,
            "base_p50_ns": b["p50_wall_ns"],
            "cur_p50_ns": c["p50_wall_ns"],
            "delta_ns": delta,
            "pct": round(100.0 * delta / b["p50_wall_ns"], 1)
            if b["p50_wall_ns"] else 0.0,
            "phase": worst,
            "phase_delta_ns": phase_deltas[worst],
            "phase_deltas": phase_deltas,
            "base_runs": b["count"], "cur_runs": c["count"],
        })
    rows.sort(key=lambda r: -r["delta_ns"])
    return rows


# ---------------------------------------------------------------------------
# the profiling advisor — closed rule registry
# ---------------------------------------------------------------------------

class AdvisorRule(NamedTuple):
    id: str                    # stable slug (docs table key)
    summary: str               # what the rule detects
    advice: str                # the knob/change to try
    check: Callable[[Dict[str, Any]], Optional[Dict[str, Any]]]
    # check(fp_aggregate) -> evidence dict when firing, else None


def _check_recompile_storm(a: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    d = a["dispatch"]
    storms = d.get("storms", 0)
    traces = d.get("traces", 0)
    # repeated runs of ONE fingerprint should trace once and then hit
    # the program cache; tracing on every run is a stage-cache miss
    # even when no single run was stormy enough to trip the detector
    retrace = a["count"] >= 2 and traces >= a["count"] \
        and d.get("dispatches", 0) > 0
    if storms <= 0 and not retrace:
        return None
    return {"storms": storms, "traces": traces,
            "dispatches": d.get("dispatches", 0), "runs": a["count"],
            "compile_mean_ns": a["phase_mean_ns"].get("compile", 0)}


def _check_per_buffer_upload(a: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    u = a["upload"]
    uploads = u.get("uploads", 0)
    per_buffer = u.get("per_buffer", 0)
    if uploads < 4 or per_buffer * 2 <= uploads:
        return None
    return {"uploads": uploads, "per_buffer": per_buffer,
            "share": round(per_buffer / uploads, 3)}


def _check_partition_skew(a: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    sk = a.get("skew")
    if not sk or sk.get("ratio", 0) < 4.0:
        return None
    ev = {"op": sk.get("op"), "ratio": sk.get("ratio"),
          "basis": sk.get("basis"), "partitions": sk.get("partitions"),
          "adaptive_consults": a["adaptive"].get("consults", 0),
          "skew_splits": a["adaptive"].get("skew_splits", 0)}
    # closed loop (ISSUE 19): when the capsule shows the adaptive
    # replanner never consulted, the remedy is the ONE-CONF fix — the
    # engine can split this partition itself from the same measured
    # statistics this rule fired on
    if ev["adaptive_consults"] == 0:
        ev["_advice"] = (
            "enable spark.rapids.tpu.adaptive.enabled — the runtime "
            "replanner splits the skewed partition into map-granular "
            "sub-reads from these same measured statistics")
    return ev


def _check_adaptive_demotion_storm(a: Dict[str, Any],
                                   ) -> Optional[Dict[str, Any]]:
    ad = a["adaptive"]
    demotions = ad.get("breaker_demotions", 0)
    if demotions <= 0:
        return None
    return {"breaker_demotions": demotions,
            "errors": ad.get("errors", 0),
            "skew_splits": ad.get("skew_splits", 0),
            "consults": ad.get("consults", 0)}


def _check_pipeline_stall(a: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    wall = a["p50_wall_ns"]
    stall = a["phase_mean_ns"].get("pipeline-stall", 0)
    if wall <= 0 or stall * 100 < wall * 30:
        return None
    return {"stall_mean_ns": stall, "p50_wall_ns": wall,
            "share": round(stall / wall, 3)}


def _check_ici_eligible(a: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    if a["mesh_devices"] < 2:
        return None
    sh, ici = a["shuffle"], a["ici"]
    host_bytes = sh.get("bytes", 0)
    if host_bytes <= 0 or ici.get("rounds", 0) > 0 \
            or ici.get("fallbacks", 0) > 0:
        return None
    return {"mesh_devices": a["mesh_devices"],
            "host_shuffle_bytes": host_bytes,
            "ici_rounds": 0}


def _check_encoded_scan(a: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    en = a["encoded"]
    if en.get("cols_encoded", 0) > 0:
        return None
    sbytes = en.get("scan_string_bytes", 0)
    ubytes = a["upload"].get("bytes", 0)
    # fire only when the decoded string width is a material share of
    # what actually crossed the host->device link
    if sbytes <= 0 or ubytes <= 0 or sbytes * 2 < ubytes:
        return None
    return {"scan_string_bytes": sbytes, "upload_bytes": ubytes,
            "share": round(sbytes / ubytes, 3)}


def _check_straggler_prone(a: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    sp = a["speculation"]
    wall = a["p50_wall_ns"]
    wait = a["phase_mean_ns"].get("spec-wait", 0)
    denied, wins = sp.get("spec_denied", 0), sp.get("spec_wins", 0)
    # fire on either face of straggler exposure: wall-clock spent past
    # the measured p95 bound, or the in-flight budget repeatedly
    # refusing to race a straggler it detected
    slow = wall > 0 and wait * 100 >= wall * 10
    starved = denied > 0 and denied > wins
    if not (slow or starved):
        return None
    return {"spec_wait_mean_ns": wait, "p50_wall_ns": wall,
            "share": round(wait / wall, 3) if wall else 0.0,
            "spec_launched": sp.get("spec_launched", 0),
            "spec_wins": wins, "spec_denied": denied}


def _check_quota_spills(a: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    spills = a["workload"].get("quota_spills", 0)
    total = a.get("_total_quota_spills", spills)
    if spills <= 0 or spills * 2 <= total:
        return None
    return {"quota_spills": spills, "all_plans": total,
            "spill_bytes": a["spill_bytes"]}


#: the closed advisor registry — one row per rule in the
#: docs/robustness.md advisor table (lint: tests/test_docs_lint.py)
ADVISOR_RULES: tuple = (
    AdvisorRule(
        "recompile-storm",
        "a plan that recompiles across runs (dispatch storms, or fresh "
        "traces on every repeat of the same fingerprint) — the "
        "stage-program cache is missing",
        "check shape-bucket churn (coalesce batchSizeBytes) and "
        "spark.rapids.tpu.stage.fusion.enabled / "
        "stage.programCache.maxSites; the program_compile events name "
        "the unstable program",
        _check_recompile_storm),
    AdvisorRule(
        "per-buffer-upload",
        "the majority of host->device uploads took the per-buffer lane "
        "instead of one packed transfer",
        "read the upload events' lane/seam fields — typically a dtype "
        "the packer skips or "
        "spark.rapids.tpu.transfer.packedUpload.enabled off",
        _check_per_buffer_upload),
    AdvisorRule(
        "partition-skew",
        "one exchange partition carries >= 4x the median partition "
        "(max/median over exact per-partition totals)",
        "pre-split hot keys or broadcast the small side "
        "(spark.rapids.sql.broadcastSizeThreshold); the skew op names "
        "the exchange",
        _check_partition_skew),
    AdvisorRule(
        "adaptive-demotion-storm",
        "the adaptive replan lane repeatedly stood down (open "
        "`adaptive` breaker) while serving this plan — its decisions "
        "are misfiring, not helping",
        "raise spark.rapids.tpu.adaptive.skewedPartitionFactor so "
        "only extreme skew triggers replanning, or pin "
        "spark.rapids.tpu.adaptive.enabled off for this workload; the "
        "adaptive_demote events carry the failing decision",
        _check_adaptive_demotion_storm),
    AdvisorRule(
        "pipeline-stall",
        "the query spends >= 30% of wall-clock blocked on pipeline "
        "producers (consumer starvation)",
        "raise spark.rapids.tpu.pipeline.depth so producers run "
        "further ahead, or widen the slow producer stage",
        _check_pipeline_stall),
    AdvisorRule(
        "ici-eligible",
        "a multi-device mesh moved shuffle bytes over the host "
        "serialize lane with ZERO ICI collective rounds",
        "enable spark.rapids.tpu.shuffle.ici.enabled — the "
        "device-resident all-to-all lane keeps map output in HBM",
        _check_ici_eligible),
    AdvisorRule(
        "quota-spill-dominance",
        "one plan triggered the majority of the workload governor's "
        "quota-triggered self-spills",
        "raise spark.rapids.tpu.workload.memoryQuotaFraction or lower "
        "this plan's concurrency share — it is thrashing its own "
        "working set",
        _check_quota_spills),
    AdvisorRule(
        "straggler-prone",
        "the plan's shuffle reads repeatedly outlive their measured "
        "p95 straggler bound (spec-wait >= 10% of wall, or speculation "
        "denials outnumber wins)",
        "raise spark.rapids.tpu.shuffle.speculation.maxInFlight so "
        "denied stragglers get a duplicate raced instead of being "
        "waited out, and check the storage path feeding the shuffle "
        "dirs; if wins dominate, the duplicates are already saving "
        "the tail",
        _check_straggler_prone),
    AdvisorRule(
        "encoded-scan-eligible",
        "scans shipped decoded string bytes that dominate the "
        "host->device upload volume while keeping ZERO columns "
        "dictionary-encoded",
        "enable spark.rapids.tpu.scan.encoded.enabled — Parquet "
        "already ships these columns dictionary-encoded; the encoded "
        "lane uploads the i32 code lane plus the dictionary and "
        "materializes late through the gather engine",
        _check_encoded_scan),
)


def advise(agg: Dict[str, Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Run every rule over every per-fingerprint aggregate; one finding
    per (rule, fingerprint) that fires, evidence attached."""
    total_quota = sum(a["workload"].get("quota_spills", 0)
                     for a in agg.values())
    findings: List[Dict[str, Any]] = []
    for fp, a in sorted(agg.items()):
        a["_total_quota_spills"] = total_quota
        for rule in ADVISOR_RULES:
            ev = rule.check(a)
            if ev is not None:
                # a check may override the static remedy with a
                # sharper, evidence-specific one (the partition-skew
                # one-conf adaptive fix)
                advice = ev.pop("_advice", rule.advice)
                findings.append({"rule": rule.id, "fingerprint": fp,
                                 "summary": rule.summary,
                                 "advice": advice, "evidence": ev})
        del a["_total_quota_spills"]
    return findings


# ---------------------------------------------------------------------------
# rendering
# ---------------------------------------------------------------------------

def _fmt_ns(ns: float) -> str:
    if abs(ns) < 1_000:
        return f"{ns:.0f}ns"
    if abs(ns) < 1_000_000:
        return f"{ns / 1_000:.1f}us"
    if abs(ns) < 1_000_000_000:
        return f"{ns / 1_000_000:.1f}ms"
    return f"{ns / 1_000_000_000:.2f}s"


def build_summary(directory: str, top: int = 20,
                  base_dir: Optional[str] = None) -> Dict[str, Any]:
    """The whole report as one JSON-able object (the --format json
    payload, and the import surface tests assert on)."""
    capsules = read_capsules(directory)
    agg = aggregate(capsules)
    out: Dict[str, Any] = {
        "dir": directory,
        "capsules": len(capsules),
        "plans": sorted(agg.values(),
                        key=lambda a: -a["p50_wall_ns"])[:top],
        "advisor": advise(agg),
    }
    if base_dir is not None:
        base_agg = aggregate(read_capsules(base_dir))
        out["base_dir"] = base_dir
        out["diff"] = diff_report(base_agg, agg)[:top]
    return out


def render_text(summary: Dict[str, Any]) -> str:
    lines: List[str] = []
    lines.append(f"query history: {summary['capsules']} capsule(s) "
                 f"in {summary['dir']}")
    lines.append("")
    lines.append("== plans (by p50 wall) ==")
    lines.append(f"{'fingerprint':<14} {'runs':>4} {'ok':>3} "
                 f"{'p50':>9} {'p95':>9} {'top phase':<18} {'share':>6}")
    for a in summary["plans"]:
        means = a["phase_mean_ns"]
        top_phase = max(means, key=means.__getitem__) if means else "-"
        share = means.get(top_phase, 0) / a["p50_wall_ns"] \
            if a["p50_wall_ns"] else 0.0
        lines.append(
            f"{a['fingerprint'][:12]:<14} {a['count']:>4} {a['ok']:>3} "
            f"{_fmt_ns(a['p50_wall_ns']):>9} "
            f"{_fmt_ns(a['p95_wall_ns']):>9} {top_phase:<18} "
            f"{share:>5.0%}")
    if "diff" in summary:
        lines.append("")
        lines.append(f"== regressions vs {summary['base_dir']} "
                     f"(by p50 delta) ==")
        lines.append(f"{'fingerprint':<14} {'base p50':>9} "
                     f"{'cur p50':>9} {'delta':>9} {'pct':>7} "
                     f"{'moved phase':<18}")
        for r in summary["diff"]:
            lines.append(
                f"{r['fingerprint'][:12]:<14} "
                f"{_fmt_ns(r['base_p50_ns']):>9} "
                f"{_fmt_ns(r['cur_p50_ns']):>9} "
                f"{_fmt_ns(r['delta_ns']):>9} {r['pct']:>6.1f}% "
                f"{r['phase']:<18} (+{_fmt_ns(r['phase_delta_ns'])})")
    lines.append("")
    findings = summary["advisor"]
    lines.append(f"== advisor: {len(findings)} finding(s) ==")
    for f in findings:
        lines.append(f"[{f['rule']}] plan {f['fingerprint'][:12]}")
        lines.append(f"    {f['summary']}")
        lines.append(f"    evidence: {json.dumps(f['evidence'])}")
        lines.append(f"    try: {f['advice']}")
    return "\n".join(lines) + "\n"


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("dir", help="history dir "
                    "(spark.rapids.tpu.history.dir)")
    ap.add_argument("--diff", metavar="BASE",
                    help="baseline history dir: rank per-plan p50 "
                    "regressions by the phase that moved")
    ap.add_argument("--top", type=int, default=20)
    ap.add_argument("--format", choices=("text", "json"), default="text")
    args = ap.parse_args(argv)
    summary = build_summary(args.dir, top=args.top, base_dir=args.diff)
    if not summary["capsules"]:
        print("no capsules found "
              "(spark.rapids.tpu.history.enabled?)", file=sys.stderr)
        return 1
    if args.format == "json":
        json.dump(summary, sys.stdout, indent=2, default=str)
        sys.stdout.write("\n")
    else:
        sys.stdout.write(render_text(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
