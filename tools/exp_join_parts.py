"""Time the join's three compiled phases separately on the chip."""

import sys
import time

sys.path.insert(0, "/root/repo")

import numpy as np
import jax
import jax.numpy as jnp

import bench
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import Column, bucket_capacity
from spark_rapids_tpu.exec.basic import FilterExec, InMemoryScanExec
from spark_rapids_tpu.exec.joins import HashJoinExec
from spark_rapids_tpu.expr.core import col, lit
from spark_rapids_tpu.types import DOUBLE, INT, LONG, Schema, StructField

d = bench.build_q3_data()
o_schema = Schema((StructField("o_orderkey", LONG), StructField("o_flag", INT)))
l_schema = Schema((StructField("l_orderkey", LONG),
                   StructField("l_price", DOUBLE),
                   StructField("l_disc", DOUBLE),
                   StructField("l_flag", INT)))


def mk_batch(schema, n):
    cap = bucket_capacity(n)
    cols = [Column.from_numpy(d[f.name], f.data_type, capacity=cap)
            for f in schema.fields]
    return ColumnarBatch(cols, n, schema)


orders = mk_batch(o_schema, bench.N_ORDERS)
lines = mk_batch(l_schema, bench.N_LINES)

o_scan = FilterExec(col("o_flag") < lit(5),
                    InMemoryScanExec([orders], o_schema))
l_scan = FilterExec(col("l_flag") != lit(0),
                    InMemoryScanExec([lines], l_schema))
join = HashJoinExec(l_scan, o_scan, [col("l_orderkey")],
                    [col("o_orderkey")], "inner", build_side="right")

o_filtered = list(o_scan.execute())[0]
l_filtered = list(l_scan.execute())[0]
jax.block_until_ready(o_filtered.columns[0].data)


def timeit(name, fn, iters=10):
    r = fn()
    jax.block_until_ready(jax.tree_util.tree_leaves(r))
    t0 = time.perf_counter()
    for _ in range(iters):
        r = fn()
        jax.block_until_ready(jax.tree_util.tree_leaves(r))
    dt = (time.perf_counter() - t0) / iters * 1e3
    print(f"{name:28s} {dt:9.2f} ms")
    return r


bt = timeit("build_kernel (512K)", lambda: join._jit_build(o_filtered))
cres = timeit("counts_kernel (2M)", lambda: join._jit_counts(bt, l_filtered))
lo, counts, skeys, total_dev, needs = cres
total, needs_h = jax.device_get((total_dev, needs))
cand_cap = bucket_capacity(max(int(total), 1))
print("total candidates:", int(total), "cand_cap:", cand_cap)
bm = jnp.zeros((bt.capacity,), jnp.bool_)
timeit("probe_kernel", lambda: join._jit_probe(
    bt, o_filtered, l_filtered, (lo, counts, skeys), bm, cand_cap, (), ()))

# sub-parts: expansion and xxhash
from spark_rapids_tpu.ops.join import expand_candidates
from spark_rapids_tpu.ops.hashing import xxhash64_batch

ec = jax.jit(lambda l, c: expand_candidates(l, c, cand_cap))
timeit("expand_candidates alone", lambda: ec(lo, counts))
kc = [l_filtered.columns[0]]
xh = jax.jit(lambda c: xxhash64_batch([c], seed=1))
timeit("xxhash64 2M i64", lambda: xh(kc[0]))
from spark_rapids_tpu.exec.basic import FilterExec as _F
timeit("filter 2M (scan+filter)", lambda: list(l_scan.execute())[0])
