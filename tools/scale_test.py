"""Scale-test harness — the reference's integration_tests scaletest
(ScaleTest.scala CLI + QuerySpecs.scala + TestReport.scala; SURVEY §4.3):
generate tables at a scale factor, run a fixed query suite, write a JSON
timing report.

Usage:
    python tools/scale_test.py [--scale 1.0] [--out report.json]
                               [--queries q1,q3,...] [--platform cpu|default]

Tables (scaled by --scale, base ~1M rows):
    facts(k long, cat string, v double, ts timestamp)
    dims(k long, name string, weight double)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def build_session(platform: str):
    import jax
    if platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
    from spark_rapids_tpu.api.session import TpuSession
    return TpuSession()


def gen_tables(sess, scale: float):
    from spark_rapids_tpu.types import (DOUBLE, LONG, STRING, TIMESTAMP,
                                        Schema, StructField)
    n_facts = int(1_000_000 * scale)
    n_dims = max(1000, int(10_000 * scale))
    rng = np.random.default_rng(7)
    facts = sess.from_pydict({
        "k": rng.integers(0, n_dims, n_facts).tolist(),
        "cat": [("c%d" % x) for x in rng.integers(0, 23, n_facts)],
        "v": (rng.random(n_facts) * 100).tolist(),
        "ts": rng.integers(1_500_000_000_000_000, 1_700_000_000_000_000,
                           n_facts).tolist(),
    }, Schema((StructField("k", LONG), StructField("cat", STRING),
               StructField("v", DOUBLE), StructField("ts", TIMESTAMP))))
    dims = sess.from_pydict({
        "k": list(range(n_dims)),
        "name": [f"dim-{i}" for i in range(n_dims)],
        "weight": (rng.random(n_dims)).tolist(),
    }, Schema((StructField("k", LONG), StructField("name", STRING),
               StructField("weight", DOUBLE))))
    return facts, dims, n_facts


def query_suite(F, col, lit):
    """Name -> (facts, dims) -> collected result. Mirrors the reference
    QuerySpecs: scan/filter/project, group-by, join, window-ish sort."""
    return {
        "q1_filter_project": lambda f, d:
            f.filter(col("v") > lit(50.0))
             .select((col("v") * lit(2.0)).alias("v2")).count(),
        "q2_groupby": lambda f, d:
            f.group_by("cat").agg((F.sum(col("v")), "s"),
                                  (F.count(), "c")).collect(),
        "q3_join_agg": lambda f, d:
            f.join(d, on="k").group_by("cat")
             .agg((F.sum(col("weight")), "w")).collect(),
        "q4_sort_limit": lambda f, d:
            f.sort(("v", False)).limit(100).collect(),
        "q5_distinct": lambda f, d:
            f.select(col("cat")).distinct().count(),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=float, default=0.1)
    ap.add_argument("--out", default="scale_report.json")
    ap.add_argument("--queries", default="")
    ap.add_argument("--platform", default="cpu",
                    choices=("cpu", "default"),
                    help="cpu pins the CPU backend; default uses whatever "
                         "jax selects (the TPU under axon)")
    args = ap.parse_args()

    sess = build_session(args.platform)
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.functions import col
    from spark_rapids_tpu.expr.core import lit

    t0 = time.perf_counter()
    facts, dims, n_facts = gen_tables(sess, args.scale)
    gen_s = time.perf_counter() - t0

    suite = query_suite(F, col, lit)
    wanted = [q.strip() for q in args.queries.split(",") if q.strip()] \
        or list(suite)
    report = {"scale": args.scale, "rows": n_facts,
              "datagen_seconds": round(gen_s, 3), "queries": []}
    for name in wanted:
        fn = suite[name]
        t0 = time.perf_counter()      # cold (includes compile)
        fn(facts, dims)
        cold = time.perf_counter() - t0
        t0 = time.perf_counter()      # warm (compiled)
        fn(facts, dims)
        warm = time.perf_counter() - t0
        report["queries"].append({
            "name": name, "cold_seconds": round(cold, 3),
            "warm_seconds": round(warm, 3),
            "rows_per_second": round(n_facts / max(warm, 1e-9))})
        print(f"{name}: cold={cold:.2f}s warm={warm:.2f}s")
    with open(args.out, "w") as f:
        json.dump(report, f, indent=2)
    print(f"report -> {args.out}")


if __name__ == "__main__":
    main()
