"""Microbenchmark for the bounded pipeline stage boundary (ISSUE 3
satellite): drives a synthetic slow-producer / slow-consumer pair
through `exec.pipeline.pipelined()` and reports achieved overlap
against the ideal `max(P, C)` bound.

The workload is pure sleeps (no jax, no numpy on the hot path), so the
numbers measure exactly the boundary: a producer that takes
`items * produce_s` and a consumer that takes `items * consume_s` run
in `P + C` when synchronous; a perfect pipeline runs them in
`max(P, C)`. The achieved overlap ratio is

    overlap = (sync_s - pipelined_s) / min(P, C)      (1.0 = perfect)

and the stage's own stall counters reconcile with it: the pipelined
wall is ~`C + wait_ns` seen from the consumer and ~`P + full_ns` seen
from the producer. With an event log enabled the same totals arrive as
`pipeline_wait` / `pipeline_full` records, which this tool cross-checks.

Usage:
    python tools/pipeline_bench.py [--items N] [--produce-ms F]
        [--consume-ms F] [--depth D] [--events DIR]

Stdlib-only workload and reporting — the only non-stdlib import is the
engine's own `exec.pipeline` module under test (no pyarrow, no numpy on
the hot path).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Any, Dict, Optional

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _produce(items: int, produce_s: float):
    for i in range(items):
        time.sleep(produce_s)
        yield i


def _drive(it, consume_s: float) -> int:
    n = 0
    for item in it:
        time.sleep(consume_s)
        n += 1
    return n


def run_bench(items: int = 30, produce_s: float = 0.01,
              consume_s: float = 0.01, depth: int = 2,
              events_dir: Optional[str] = None) -> Dict[str, Any]:
    from spark_rapids_tpu.exec.pipeline import pipelined
    from spark_rapids_tpu.obs import events as obs_events

    if events_dir:
        obs_events.enable(events_dir, "MODERATE")
    run_start_ns = time.time_ns()

    # synchronous baseline: P + C
    t0 = time.perf_counter()
    n_sync = _drive(_produce(items, produce_s), consume_s)
    sync_s = time.perf_counter() - t0

    # pipelined: ideally max(P, C). The synthetic stage only emits
    # event records into a log THIS tool set up (--events cross-check);
    # driven in-process by bench.py with the engine's event log active,
    # its deliberate sleep-stalls would otherwise contaminate the real
    # pipeline_wait/pipeline_full totals in the profile report.
    t0 = time.perf_counter()
    stage = pipelined(_produce(items, produce_s), depth=depth,
                      label="pipeline-bench",
                      emit_events=bool(events_dir))
    try:
        n_pipe = _drive(stage, consume_s)
    finally:
        stage.close()
    pipelined_s = time.perf_counter() - t0
    assert n_sync == n_pipe == items

    P = items * produce_s
    C = items * consume_s
    ideal_s = max(P, C)
    overlap = (sync_s - pipelined_s) / min(P, C) if min(P, C) > 0 else 0.0
    out: Dict[str, Any] = {
        "items": items,
        "produce_ms": produce_s * 1e3,
        "consume_ms": consume_s * 1e3,
        "depth": depth,
        "sync_s": round(sync_s, 4),
        "pipelined_s": round(pipelined_s, 4),
        "ideal_s": round(ideal_s, 4),
        "speedup": round(sync_s / pipelined_s, 3) if pipelined_s else 0.0,
        "overlap": round(max(0.0, min(1.0, overlap)), 3),
        "wait_ns": stage.wait_ns,
        "full_ns": stage.full_ns,
    }
    # the stage's stall counters must reconcile with the wall clock:
    # consumer wall = busy (C) + blocked-on-empty (wait_ns)
    out["consumer_wall_check_s"] = round(C + stage.wait_ns / 1e9, 4)
    if events_dir:
        out["events"] = _event_totals(events_dir, run_start_ns)
        obs_events.reset_event_bus()
    return out


def _event_totals(events_dir: str, since_ns: int) -> Dict[str, int]:
    """Sum the pipeline_wait/pipeline_full records THIS run wrote
    (cross-check: they carry the same totals as the stage counters).
    `since_ns` excludes records a previous run left in a reused dir."""
    wait = full = 0
    for name in os.listdir(events_dir):
        if not name.endswith(".jsonl"):
            continue
        with open(os.path.join(events_dir, name)) as f:
            for ln in f:
                try:
                    rec = json.loads(ln)
                except ValueError:
                    continue
                if rec.get("stage") != "pipeline-bench" \
                        or (rec.get("ts_ns") or 0) < since_ns:
                    continue
                if rec.get("kind") == "pipeline_wait":
                    wait += rec.get("wait_ns") or 0
                elif rec.get("kind") == "pipeline_full":
                    full += rec.get("full_ns") or 0
    return {"pipeline_wait_ns": wait, "pipeline_full_ns": full}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--items", type=int, default=30)
    ap.add_argument("--produce-ms", type=float, default=10.0)
    ap.add_argument("--consume-ms", type=float, default=10.0)
    ap.add_argument("--depth", type=int, default=2)
    ap.add_argument("--events", default=None,
                    help="also write + cross-check an event log here")
    args = ap.parse_args(argv)
    out = run_bench(args.items, args.produce_ms / 1e3,
                    args.consume_ms / 1e3, args.depth,
                    events_dir=args.events)
    print(json.dumps(out, indent=2))
    ok = out["speedup"] >= 1.5
    print(f"speedup {out['speedup']}x vs synchronous "
          f"(overlap {out['overlap']} of ideal max(P,C)="
          f"{out['ideal_s']}s) -> {'OK' if ok else 'BELOW 1.5x TARGET'}")
    if out.get("events") is not None:
        drift = abs(out["events"]["pipeline_wait_ns"] - out["wait_ns"])
        print(f"event reconcile: pipeline_wait {out['events']}"
              f" vs stage wait_ns={out['wait_ns']} (drift {drift}ns)")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
