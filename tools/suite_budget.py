"""Render a pytest `--durations` report into the tier-1 time-budget
table (ISSUE 17 satellite): the suite runs under a hard `timeout 870`
gate (ROADMAP.md), so every second a test spends is budget another test
cannot — this tool shows where the seconds go and how much headroom the
gate has left.

Usage:
    python -m pytest tests/ -q -m 'not slow' --durations=0 | tee run.log
    python tools/suite_budget.py run.log [--budget 870] [--top 20]
                                 [--format text|json]

Parses the `== slowest durations ==` section (call/setup/teardown
rows), aggregates per test and per file, and prints the top-N table
with each entry's share of the gate. Exits 1 when the measured total
exceeds `--warn-fraction` (default 0.8) of the budget — the early
warning that the next added test pushes tier-1 over the timeout.

Stdlib only; importable (`parse_durations`, `build_budget`) for tests.
"""

from __future__ import annotations

import argparse
import json
import re
import sys
from typing import Any, Dict, List, Optional

#: the tier-1 wall-clock gate (ROADMAP.md verify recipe: `timeout 870`)
DEFAULT_BUDGET_S = 870.0

#: `0.12s call tests/test_x.py::TestC::test_y[param]`
_ROW = re.compile(
    r"^\s*(\d+(?:\.\d+)?)s\s+(call|setup|teardown)\s+(\S+)\s*$")


def parse_durations(lines) -> List[Dict[str, Any]]:
    """Every duration row in a pytest output: [{seconds, stage, test}].
    Rows outside the durations section never match the shape, so the
    whole log can be fed in unfiltered."""
    out: List[Dict[str, Any]] = []
    for ln in lines:
        m = _ROW.match(ln)
        if m:
            out.append({"seconds": float(m.group(1)),
                        "stage": m.group(2), "test": m.group(3)})
    return out


def build_budget(rows: List[Dict[str, Any]], budget_s: float,
                 top: int = 20) -> Dict[str, Any]:
    """The budget report as one JSON-able object: per-test totals
    (call+setup+teardown merged), per-file totals, and the gate
    arithmetic. NOTE: pytest truncates sub-threshold rows unless
    `--durations=0`; `measured_s` is a floor, not the suite wall."""
    by_test: Dict[str, float] = {}
    by_file: Dict[str, float] = {}
    for r in rows:
        by_test[r["test"]] = by_test.get(r["test"], 0.0) + r["seconds"]
        fname = r["test"].split("::", 1)[0]
        by_file[fname] = by_file.get(fname, 0.0) + r["seconds"]
    measured = sum(by_test.values())
    tests = sorted(by_test.items(), key=lambda kv: -kv[1])
    files = sorted(by_file.items(), key=lambda kv: -kv[1])
    return {
        "budget_s": budget_s,
        "measured_s": round(measured, 2),
        "budget_share": round(measured / budget_s, 3) if budget_s else 0,
        "headroom_s": round(budget_s - measured, 2),
        "rows": len(rows),
        "top_tests": [{"test": t, "seconds": round(s, 2),
                       "share": round(s / budget_s, 4) if budget_s else 0}
                      for t, s in tests[:top]],
        "top_files": [{"file": f, "seconds": round(s, 2),
                       "share": round(s / budget_s, 4) if budget_s else 0}
                      for f, s in files[:top]],
    }


def render_text(b: Dict[str, Any]) -> str:
    lines = [
        f"tier-1 time budget: {b['measured_s']:.1f}s measured of "
        f"{b['budget_s']:.0f}s gate "
        f"({b['budget_share']:.0%} used, {b['headroom_s']:.1f}s "
        f"headroom)",
        "",
        "== top tests ==",
        f"{'seconds':>8} {'share':>6}  test",
    ]
    for r in b["top_tests"]:
        lines.append(f"{r['seconds']:>7.2f}s {r['share']:>6.1%}  "
                     f"{r['test']}")
    lines.append("")
    lines.append("== top files ==")
    lines.append(f"{'seconds':>8} {'share':>6}  file")
    for r in b["top_files"]:
        lines.append(f"{r['seconds']:>7.2f}s {r['share']:>6.1%}  "
                     f"{r['file']}")
    return "\n".join(lines) + "\n"


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("log", help="pytest output containing a "
                    "--durations section ('-' = stdin)")
    ap.add_argument("--budget", type=float, default=DEFAULT_BUDGET_S)
    ap.add_argument("--top", type=int, default=20)
    ap.add_argument("--warn-fraction", type=float, default=0.8,
                    help="exit 1 when measured time exceeds this "
                    "fraction of the budget")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    args = ap.parse_args(argv)
    f = sys.stdin if args.log == "-" else open(args.log)
    try:
        rows = parse_durations(f)
    finally:
        if f is not sys.stdin:
            f.close()
    if not rows:
        print("no duration rows found (run pytest with --durations=N)",
              file=sys.stderr)
        return 1
    b = build_budget(rows, args.budget, top=args.top)
    if args.format == "json":
        json.dump(b, sys.stdout, indent=2)
        sys.stdout.write("\n")
    else:
        sys.stdout.write(render_text(b))
    return 1 if b["measured_s"] > args.warn_fraction * args.budget else 0


if __name__ == "__main__":
    sys.exit(main())
