"""Render telemetry samples as Prometheus text exposition format
(ISSUE 11 tentpole part 2: the scrape side of the telemetry plane).

The engine's telemetry registry (obs/telemetry.py) flushes one
`telemetry_sample` JSONL record per sampler tick into the event log.
This CLI turns a log (or a whole rotated set — any member works) into
Prometheus text format a scrape pipeline ingests:

    python tools/telemetry_export.py EVENTS.jsonl            # newest
    python tools/telemetry_export.py EVENTS.jsonl --all      # every one

Gauges are named `spark_rapids_tpu_<series>` with dots mapped to
underscores; the per-owner HBM attribution exports as
`spark_rapids_tpu_hbm_owner_bytes{tier="device|host",owner="..."}`.
Stdlib only — runs anywhere the log lands; importable as
`to_prometheus(sample)` for tests and embedding.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Any, Dict, List, Optional

_HERE = os.path.dirname(os.path.abspath(__file__))
if _HERE not in sys.path:
    sys.path.insert(0, _HERE)
from profile_report import read_event_files  # noqa: E402

PREFIX = "spark_rapids_tpu"


def _metric(name: str) -> str:
    return f"{PREFIX}_{name.replace('.', '_').replace('-', '_')}"


def _sample_lines(sample: Dict[str, Any]) -> List[tuple]:
    """One sample -> [(metric, type, labeled-name, value, ts-suffix)]."""
    out: List[tuple] = []
    ts_ms = sample.get("ts_ms")
    suffix = f" {ts_ms}" if ts_ms is not None else ""
    for key in sorted(sample):
        val = sample[key]
        if key in ("ts_ms", "ts_ns", "kind", "query", "counters",
                   "hbm_by_owner"):
            continue
        if isinstance(val, bool) or not isinstance(val, (int, float)):
            continue
        m = _metric(key)
        out.append((m, "gauge", m, val, suffix))
    owners = sample.get("hbm_by_owner") or {}
    if owners:
        m = _metric("hbm.owner_bytes")
        for tier in ("device", "host"):
            for owner, nbytes in sorted((owners.get(tier) or {}).items()):
                out.append((m, "gauge",
                            f'{m}{{tier="{tier}",owner="{owner}"}}',
                            nbytes, suffix))
    counters = sample.get("counters") or {}
    for key in sorted(counters):
        m = _metric(f"counter.{key}")
        out.append((m, "counter", m, counters[key], suffix))
    return out


def render(samples: List[Dict[str, Any]]) -> str:
    """Render one OR several samples as valid text exposition: each
    metric's `# TYPE` line appears exactly once, with one timestamped
    line per sample under it (the layout `promtool tsdb
    create-blocks-from openmetrics` style backfill consumes; a single
    sample is a plain Prometheus scrape page)."""
    by_metric: Dict[str, List[str]] = {}
    types: Dict[str, str] = {}
    order: List[str] = []
    for s in samples:
        for metric, typ, labeled, val, suffix in _sample_lines(s):
            if metric not in types:
                types[metric] = typ
                order.append(metric)
            by_metric.setdefault(metric, []).append(
                f"{labeled} {val}{suffix}")
    lines: List[str] = []
    for metric in order:
        lines.append(f"# TYPE {metric} {types[metric]}")
        lines.extend(by_metric[metric])
    return "\n".join(lines) + "\n"


def to_prometheus(sample: Dict[str, Any]) -> str:
    """One telemetry_sample record -> Prometheus text format."""
    return render([sample])


def samples_from_events(events: List[Dict[str, Any]]
                        ) -> List[Dict[str, Any]]:
    return [e for e in events if e.get("kind") == "telemetry_sample"]


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("log", help="events-*.jsonl file (obs/events.py); "
                               "a rotated set is read in order")
    ap.add_argument("--all", action="store_true",
                    help="export every sample, oldest first "
                         "(default: only the newest)")
    args = ap.parse_args(argv)
    samples = samples_from_events(read_event_files(args.log))
    if not samples:
        print("no telemetry_sample records found "
              "(spark.rapids.tpu.telemetry.enabled?)", file=sys.stderr)
        return 1
    sys.stdout.write(render(samples if args.all else samples[-1:]))
    return 0


if __name__ == "__main__":
    sys.exit(main())
