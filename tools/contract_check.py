#!/usr/bin/env python3
"""Engine contract analyzer CLI (ISSUE 12).

Runs the spark_rapids_tpu.analysis rules over the package (plus tools/
and bench.py) and reports findings not covered by a justified
suppression or the checked-in baseline.

Usage:
    python tools/contract_check.py [paths...]
        [--format text|json] [--baseline PATH | --baseline write]
        [--rules id,id,...]

Exit codes: 0 = clean (all findings suppressed/baselined, no stale or
invalid baseline entries), 1 = new findings / baseline problems,
2 = usage error. `--baseline write` accepts the current findings into
the baseline file, preserving existing justifications and stamping new
entries UNREVIEWED (the tier-1 baseline lint rejects that stamp, so a
human must justify each before it can land). Stdlib-only; in-process
use: tests/test_contract_check.py drives main() directly as the CI
gate.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parents[1]
DEFAULT_BASELINE = ROOT / "tools" / "contract_baseline.json"
if str(ROOT) not in sys.path:
    sys.path.insert(0, str(ROOT))


def build_report(paths=None, rules=None, registry=None):
    """Analyze `paths` (default: the package scan set). Importable
    entry for tests and tooling."""
    from spark_rapids_tpu import analysis
    files = [Path(p) for p in paths] if paths else \
        analysis.default_source_files(ROOT)
    expanded = []
    for p in files:
        if p.is_dir():
            expanded.extend(sorted(p.rglob("*.py")))
        else:
            expanded.append(p)
    return analysis.analyze_paths(expanded, ROOT, registry=registry,
                                  rules=rules)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="contract_check",
        description="AST-based engine contract analyzer")
    ap.add_argument("paths", nargs="*", help="files/dirs to analyze "
                    "(default: spark_rapids_tpu/, tools/, bench.py)")
    ap.add_argument("--format", choices=("text", "json"),
                    default="text")
    ap.add_argument("--baseline", default=str(DEFAULT_BASELINE),
                    help="baseline file, or the word 'write' to "
                    "accept current findings into the default file")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids to run (default all)")
    try:
        args = ap.parse_args(argv)
    except SystemExit as e:
        return 2 if e.code not in (0, None) else 0

    from spark_rapids_tpu.analysis import core as acore

    rules = [r.strip() for r in args.rules.split(",")] \
        if args.rules else None
    report = build_report(args.paths or None, rules=rules)
    findings = report.sorted_findings()

    if args.baseline == "write":
        if args.paths or args.rules:
            # a scoped run sees only a slice of the findings — writing
            # it would silently drop every out-of-scope entry AND its
            # hand-written justification
            print("contract_check: --baseline write requires the full "
                  "default scan set (no paths, no --rules)",
                  file=sys.stderr)
            return 2
        prev = acore.load_baseline(DEFAULT_BASELINE)
        entries = acore.write_baseline(DEFAULT_BASELINE, findings, prev)
        print(f"baseline: wrote {len(entries)} entr"
              f"{'y' if len(entries) == 1 else 'ies'} to "
              f"{DEFAULT_BASELINE}")
        unreviewed = [fp for fp, e in entries.items()
                      if e["why"] == acore.UNREVIEWED_WHY]
        for fp in unreviewed:
            print(f"  UNREVIEWED (justify before commit): {fp}")
        return 0

    baseline = acore.load_baseline(Path(args.baseline))
    new, stale, lint = acore.apply_baseline(findings, baseline)
    problems = new + lint

    if args.format == "json":
        print(json.dumps({
            "version": 1,
            "files_scanned": report.files_scanned,
            "findings": [f.to_dict() for f in new],
            "baseline_lint": [f.to_dict() for f in lint],
            "stale_baseline": stale,
            "suppressed": len(report.suppressed),
            "baselined": len(findings) - len(new),
            "exit": 1 if (problems or stale) else 0,
        }, indent=1, sort_keys=True))
    else:
        for f in new:
            print(f.render())
        for f in lint:
            print(f.render())
        for fp in stale:
            print(f"stale baseline entry (finding fixed — delete it "
                  f"or shrink its count): {fp}")
        print(f"contract_check: {report.files_scanned} files, "
              f"{len(new)} new finding(s), "
              f"{len(findings) - len(new)} baselined, "
              f"{len(report.suppressed)} suppressed, "
              f"{len(stale)} stale baseline entr"
              f"{'y' if len(stale) == 1 else 'ies'}")
    return 1 if (problems or stale) else 0


if __name__ == "__main__":
    sys.exit(main())
