"""Render a spark_rapids_tpu event log (JSONL, obs/events.py) into a
top-N operator time/bytes table — the offline half of the query-profile
surface (ISSUE 2; reference analog: the qualification/profiling tool
over Spark event logs).

Usage:
    python tools/profile_report.py EVENTS.jsonl [--top N] [--query QID]

Reads `op_close` spans (cumulative wall-ns / rows / batches per
operator instance), `op_batch` spans (per-batch bytes), and the
query/task events (spill, oom_retry, semaphore_acquire, exchange) and
prints one aggregated report. Wall-ns are INCLUSIVE of child time (the
pull model), so percentages are of the slowest root span, not a sum.
Stdlib only — runs anywhere the log file lands.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, Iterable, List, Optional


def read_events(lines: Iterable[str]) -> List[Dict[str, Any]]:
    out = []
    bad = 0
    for ln in lines:
        ln = ln.strip()
        if not ln:
            continue
        try:
            out.append(json.loads(ln))
        except ValueError:
            # a SIGKILL'd process can leave a truncated final line; the
            # parseable prefix is exactly what a crash profile needs
            bad += 1
    if bad:
        print(f"warning: skipped {bad} unparseable line(s)",
              file=sys.stderr)
    return out


def _fmt_ns(ns: float) -> str:
    if ns < 1_000:
        return f"{ns:.0f}ns"
    if ns < 1_000_000:
        return f"{ns / 1_000:.1f}us"
    if ns < 1_000_000_000:
        return f"{ns / 1_000_000:.1f}ms"
    return f"{ns / 1_000_000_000:.2f}s"


def _fmt_bytes(b: float) -> str:
    if b < (1 << 10):
        return f"{b:.0f}B"
    if b < (1 << 20):
        return f"{b / (1 << 10):.1f}KB"
    if b < (1 << 30):
        return f"{b / (1 << 20):.1f}MB"
    return f"{b / (1 << 30):.2f}GB"


def build_report(events: List[Dict[str, Any]], top: int = 10,
                 query: Optional[int] = None) -> str:
    if query is not None:
        events = [e for e in events if e.get("query") == query]

    # per-operator-instance aggregation
    ops: Dict[Any, Dict[str, Any]] = {}
    for e in events:
        kind = e.get("kind")
        if kind not in ("op_close", "op_batch"):
            continue
        key = (e.get("op"), e.get("op_id"))
        agg = ops.setdefault(key, {"op": e.get("op"),
                                   "op_id": e.get("op_id"),
                                   "wall_ns": 0, "rows": 0, "batches": 0,
                                   "bytes": 0})
        if kind == "op_close":
            agg["wall_ns"] += e.get("wall_ns") or 0
            agg["rows"] += e.get("rows") or 0
            agg["batches"] += e.get("batches") or 0
        else:
            agg["bytes"] += e.get("bytes") or 0

    lines: List[str] = []
    queries = sorted({e.get("query") for e in events
                      if e.get("query") is not None})
    n_end = sum(1 for e in events if e.get("kind") == "query_end")
    lines.append(f"event log: {len(events)} events, "
                 f"{len(queries)} queries ({n_end} completed)")

    rows = sorted(ops.values(), key=lambda r: -r["wall_ns"])
    total_ns = max((r["wall_ns"] for r in rows), default=0)
    if rows:
        lines.append("")
        lines.append(f"top {min(top, len(rows))} operators by inclusive "
                     "wall time:")
        hdr = (f"{'#':>3} {'operator':<28} {'id':>4} {'time':>10} "
               f"{'%root':>6} {'rows':>12} {'batches':>8} {'bytes':>10}")
        lines.append(hdr)
        lines.append("-" * len(hdr))
        for i, r in enumerate(rows[:top], 1):
            pct = 100.0 * r["wall_ns"] / total_ns if total_ns else 0.0
            lines.append(
                f"{i:>3} {r['op']:<28} "
                f"{r['op_id'] if r['op_id'] is not None else '-':>4} "
                f"{_fmt_ns(r['wall_ns']):>10} {pct:>5.1f}% "
                f"{r['rows']:>12} {r['batches']:>8} "
                f"{_fmt_bytes(r['bytes']):>10}")

    # task-scoped roll-ups
    def total(kind, field):
        return sum(e.get(field) or 0 for e in events
                   if e.get("kind") == kind)

    extras = []
    n_spill = sum(1 for e in events if e.get("kind") == "spill")
    if n_spill:
        extras.append(f"spills: {n_spill} "
                      f"({_fmt_bytes(total('spill', 'bytes'))})")
    n_retry = sum(1 for e in events if e.get("kind") == "oom_retry")
    if n_retry:
        extras.append(f"oom retries: {n_retry}")
    sem_ns = total("semaphore_acquire", "wait_ns")
    if sem_ns:
        extras.append(f"semaphore wait: {_fmt_ns(sem_ns)}")
    pipe_wait = total("pipeline_wait", "wait_ns")
    pipe_full = total("pipeline_full", "full_ns")
    n_stage = sum(1 for e in events if e.get("kind") == "pipeline_wait")
    if n_stage:
        extras.append(
            f"pipeline stages: {n_stage} (consumer stalled "
            f"{_fmt_ns(pipe_wait)} on empty, producer stalled "
            f"{_fmt_ns(pipe_full)} on full)")
    exch = total("exchange", "bytes")
    if exch:
        extras.append(f"exchange bytes: {_fmt_bytes(exch)}")
    # shuffle-write roll-up (ISSUE 9): write time split pack (device
    # partition + packed D2H) / serialize / file IO, byte and frame
    # totals, and how many maps rode the device-partition lane
    writes = [e for e in events if e.get("kind") == "shuffle_write"]
    if writes:
        n_dev = sum(1 for e in writes if e.get("lane") == "device")
        extras.append(
            f"shuffle writes: {len(writes)} maps "
            f"({_fmt_bytes(total('shuffle_write', 'bytes'))} in "
            f"{total('shuffle_write', 'frames')} frames; "
            f"{n_dev} device-partitioned; pack "
            f"{_fmt_ns(total('shuffle_write', 'pack_ns'))}, serialize "
            f"{_fmt_ns(total('shuffle_write', 'serialize_ns'))}, io "
            f"{_fmt_ns(total('shuffle_write', 'io_ns'))})")
    n_fb = sum(1 for e in events
               if e.get("kind") in ("plan_fallback", "plan_not_on_tpu"))
    if n_fb:
        extras.append(f"plan fallback/why-not records: {n_fb}")
    # robustness roll-up (docs/robustness.md): how much chaos the run
    # absorbed, and at which recovery layer
    n_inject = sum(1 for e in events if e.get("kind") == "fault_inject")
    if n_inject:
        by_point: Dict[str, int] = {}
        for e in events:
            if e.get("kind") == "fault_inject":
                by_point[e.get("point", "?")] = \
                    by_point.get(e.get("point", "?"), 0) + 1
        detail = ", ".join(f"{p}:{n}" for p, n in sorted(by_point.items()))
        extras.append(f"injected faults: {n_inject} ({detail})")
    n_io = sum(1 for e in events if e.get("kind") == "io_retry")
    if n_io:
        extras.append(f"io retries: {n_io}")
    n_task = sum(1 for e in events if e.get("kind") == "task_retry")
    if n_task:
        extras.append(f"task re-executions: {n_task}")
    # lifecycle-governor roll-up (ISSUE 6): cancellations by phase,
    # breaker transitions, and which recovery lane paid for failures
    cancels = [e for e in events if e.get("kind") == "query_cancelled"]
    if cancels:
        by_phase: Dict[str, int] = {}
        for e in cancels:
            by_phase[e.get("phase", "?")] = \
                by_phase.get(e.get("phase", "?"), 0) + 1
        detail = ", ".join(f"{p}:{n}" for p, n in sorted(by_phase.items()))
        extras.append(f"query cancellations: {len(cancels)} ({detail})")
    n_bopen = sum(1 for e in events if e.get("kind") == "breaker_open")
    n_bhalf = sum(1 for e in events
                  if e.get("kind") == "breaker_half_open")
    n_bclose = sum(1 for e in events if e.get("kind") == "breaker_close")
    if n_bopen or n_bhalf or n_bclose:
        extras.append(f"breaker trips: {n_bopen} open, {n_bhalf} "
                      f"half-open, {n_bclose} close")
    # only when the partition lane actually engaged — the whole-plan
    # count already prints as "task re-executions" above, and repeating
    # it alone would state the same figure twice
    n_part = sum(1 for e in events
                 if e.get("kind") == "partition_recompute")
    if n_part:
        extras.append(f"recovery lanes: {n_part} partition-granular "
                      f"recompute(s), {n_task} whole-plan "
                      "re-execution(s)")
    # workload-governor roll-up (ISSUE 7): admission flow, sheds by
    # reason, and quota-triggered self-spills
    n_adm = sum(1 for e in events if e.get("kind") == "query_admitted")
    n_que = sum(1 for e in events if e.get("kind") == "query_queued")
    sheds = [e for e in events if e.get("kind") == "query_shed"]
    if n_adm or n_que or sheds:
        waits = [e.get("wait_ms") or 0 for e in events
                 if e.get("kind") == "query_admitted"]
        extras.append(
            f"workload admissions: {n_adm} ({n_que} queued, max wait "
            f"{max(waits) if waits else 0}ms)")
    if sheds:
        by_reason: Dict[str, int] = {}
        for e in sheds:
            by_reason[e.get("reason", "?")] = \
                by_reason.get(e.get("reason", "?"), 0) + 1
        detail = ", ".join(f"{r}:{n}"
                           for r, n in sorted(by_reason.items()))
        extras.append(f"queries shed: {len(sheds)} ({detail})")
    n_quota = sum(1 for e in events if e.get("kind") == "quota_spill")
    if n_quota:
        extras.append(f"quota spills: {n_quota} "
                      f"(over-share queries spilled their own entries)")
    n_integ = sum(1 for e in events if e.get("kind") == "integrity_fail")
    if n_integ:
        extras.append(f"integrity quarantines: {n_integ}")
    n_watch = sum(1 for e in events
                  if e.get("kind") in ("pipeline_stuck",
                                       "spill_writer_dead"))
    if n_watch:
        extras.append(f"watchdog trips: {n_watch}")
    tiers = [e for e in events if e.get("kind") == "pallas_tier"]
    if tiers:
        on = sum(1 for e in tiers if e.get("engaged"))
        extras.append(f"pallas tier decisions: {len(tiers)} "
                      f"({on} engaged)")
    # gather-engine roll-up (ISSUE 8): materializing row gathers per
    # wired operator — the count drop IS the optimization, so a bench
    # round reads it next to the pipeline/workload lines
    gstats = [e for e in events if e.get("kind") == "gather_stats"]
    if gstats:
        n_g = sum(e.get("count") or 0 for e in gstats)
        n_packed = sum(e.get("packed") or 0 for e in gstats)
        n_pallas = sum(e.get("pallas") or 0 for e in gstats)
        g_bytes = sum(e.get("bytes") or 0 for e in gstats)
        extras.append(
            f"gathers: {n_g} ({n_packed} packed rows, {n_pallas} via "
            f"the Pallas DMA kernel, ~{_fmt_bytes(g_bytes)} moved)")
    # upload-engine roll-up (ISSUE 10): host->device ingest — the
    # transfer-count drop (one per batch vs one per buffer) is the
    # optimization, so a round reads it next to the gather line
    ups = [e for e in events if e.get("kind") == "upload"]
    if ups:
        n_pk = sum(1 for e in ups if e.get("lane") == "packed")
        n_pb = len(ups) - n_pk
        u_bytes = sum(e.get("bytes") or 0 for e in ups)
        u_xfers = sum(e.get("transfers") or 0 for e in ups)
        u_ns = sum(e.get("pack_ns") or 0 for e in ups)
        extras.append(
            f"uploads: {len(ups)} batches ({n_pk} packed, {n_pb} "
            f"per-buffer; {u_xfers} h2d transfers, "
            f"{_fmt_bytes(u_bytes)}, pack {_fmt_ns(u_ns)})")
    if extras:
        lines.append("")
        lines.extend(extras)
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("log", help="events-*.jsonl file (obs/events.py)")
    ap.add_argument("--top", type=int, default=10,
                    help="operators to show (default 10)")
    ap.add_argument("--query", type=int, default=None,
                    help="restrict to one query id")
    args = ap.parse_args(argv)
    with open(args.log) as f:
        events = read_events(f)
    print(build_report(events, top=args.top, query=args.query))
    return 0


if __name__ == "__main__":
    sys.exit(main())
