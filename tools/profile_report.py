"""Render a spark_rapids_tpu event log (JSONL, obs/events.py) into a
top-N operator time/bytes table — the offline half of the query-profile
surface (ISSUE 2; reference analog: the qualification/profiling tool
over Spark event logs).

Usage:
    python tools/profile_report.py EVENTS.jsonl [--top N] [--query QID]
                                   [--format text|json]

Reads `op_close` spans (cumulative wall-ns / rows / batches per
operator instance), `op_batch` spans (per-batch bytes), and the
query/task events (spill, oom_retry, semaphore_acquire, exchange) and
prints one aggregated report. Wall-ns are INCLUSIVE of child time (the
pull model), so percentages are of the slowest root span, not a sum.

`--format json` (ISSUE 11 satellite) emits the SAME roll-ups as the
text report — top ops, pipeline overlap, gathers, shuffle writes,
uploads, robustness, workload, runtime statistics — as one JSON object
(`build_summary`), so CI and AQE tests assert on fields instead of
scraping text. Given any member of a rotated log set
(`events-<pid>-<n>.jsonl` + `.1.jsonl`, `.2.jsonl`, ... —
spark.rapids.tpu.eventLog.maxBytes), the whole set is read in rotation
order. Stdlib only — runs anywhere the log file lands.
"""

from __future__ import annotations

import argparse
import glob as _glob
import json
import os
import re
import sys
from typing import Any, Dict, Iterable, List, Optional


def read_events(lines: Iterable[str]) -> List[Dict[str, Any]]:
    out = []
    bad = 0
    for ln in lines:
        ln = ln.strip()
        if not ln:
            continue
        try:
            out.append(json.loads(ln))
        except ValueError:
            # a SIGKILL'd process can leave a truncated final line; the
            # parseable prefix is exactly what a crash profile needs
            bad += 1
    if bad:
        print(f"warning: skipped {bad} unparseable line(s)",
              file=sys.stderr)
    return out


def rotated_set(path: str) -> List[str]:
    """All members of `path`'s rotated log set, in write order (base
    file first, then `.1.jsonl`, `.2.jsonl`, ...). A non-rotated log —
    or any file that does not match the rotation naming — returns just
    itself, so every existing caller keeps working."""
    m = re.fullmatch(r"(.*?)(?:\.(\d+))?\.jsonl", path)
    if m is None:
        return [path]
    base = m.group(1)
    members = [(0, f"{base}.jsonl")]
    for p in _glob.glob(_glob.escape(base) + ".*.jsonl"):
        mm = re.fullmatch(re.escape(base) + r"\.(\d+)\.jsonl", p)
        if mm:
            members.append((int(mm.group(1)), p))
    out = [p for _n, p in sorted(members) if os.path.exists(p)]
    return out or [path]


def read_event_files(path: str) -> List[Dict[str, Any]]:
    """Read `path`'s whole rotated set in order (ISSUE 11 satellite:
    a soak's rotated log renders as one report; a truncated final line
    in any member is tolerated)."""
    events: List[Dict[str, Any]] = []
    for p in rotated_set(path):
        with open(p) as f:
            events.extend(read_events(f))
    return events


def _fmt_ns(ns: float) -> str:
    if ns < 1_000:
        return f"{ns:.0f}ns"
    if ns < 1_000_000:
        return f"{ns / 1_000:.1f}us"
    if ns < 1_000_000_000:
        return f"{ns / 1_000_000:.1f}ms"
    return f"{ns / 1_000_000_000:.2f}s"


def _fmt_bytes(b: float) -> str:
    if b < (1 << 10):
        return f"{b:.0f}B"
    if b < (1 << 20):
        return f"{b / (1 << 10):.1f}KB"
    if b < (1 << 30):
        return f"{b / (1 << 20):.1f}MB"
    return f"{b / (1 << 30):.2f}GB"


def _worst_skew(xstats: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    return max(xstats, key=lambda e: e.get("skew_ratio") or 0, default=None)


def _dispatch_rollup(compiles: List[Dict[str, Any]],
                     storms: List[Dict[str, Any]],
                     dstats: List[Dict[str, Any]],
                     top: int) -> Dict[str, Any]:
    """The `dispatch` section of build_summary: program_compile /
    recompile_storm / dispatch_stats events aggregated by program label
    and by operator."""
    by_label: Dict[str, Dict[str, Any]] = {}
    for e in compiles:
        lab = e.get("label") or "?"
        agg = by_label.setdefault(lab, {"label": lab, "compiles": 0,
                                        "programs": 0, "compile_ns": 0,
                                        "trace_ns": 0})
        agg["compiles"] += 1
        agg["programs"] += 1 if e.get("first") else 0
        agg["compile_ns"] += e.get("compile_ns") or 0
        agg["trace_ns"] += e.get("trace_ns") or 0
    top_compile = sorted(by_label.values(),
                         key=lambda r: -r["compile_ns"])[:top]
    by_op: Dict[Any, Dict[str, Any]] = {}
    for e in dstats:
        key = (e.get("op"), e.get("op_id"))
        agg = by_op.setdefault(key, {"op": e.get("op"),
                                     "op_id": e.get("op_id"),
                                     "dispatches": 0, "batches": 0,
                                     "compile_ns": 0})
        agg["dispatches"] += e.get("dispatches") or 0
        agg["batches"] += e.get("batches") or 0
        agg["compile_ns"] += e.get("compile_ns") or 0
    for r in by_op.values():
        r["dispatches_per_batch"] = (
            round(r["dispatches"] / r["batches"], 4)
            if r["batches"] else None)
    top_rate = sorted(
        by_op.values(),
        key=lambda r: -(r["dispatches_per_batch"] or 0))[:top]
    return {
        "programs_compiled": len(compiles),
        "compile_ns": sum(e.get("compile_ns") or 0 for e in compiles),
        "trace_ns": sum(e.get("trace_ns") or 0 for e in compiles),
        "top_by_compile_ns": top_compile,
        "top_by_dispatches_per_batch": top_rate,
        "storms": [{"label": e.get("label"),
                    "bucket": e.get("bucket"),
                    "traces_in_window": e.get("traces_in_window"),
                    "window_ms": e.get("window_ms")} for e in storms],
    }


def build_summary(events: List[Dict[str, Any]], top: int = 10,
                  query: Optional[int] = None) -> Dict[str, Any]:
    """THE report data: every roll-up the text renderer prints, as one
    machine-readable dict (the `--format json` payload). build_report
    renders from this, so the two formats cannot drift."""
    if query is not None:
        events = [e for e in events if e.get("query") == query]

    # per-operator-instance aggregation
    ops: Dict[Any, Dict[str, Any]] = {}
    for e in events:
        kind = e.get("kind")
        if kind not in ("op_close", "op_batch"):
            continue
        key = (e.get("op"), e.get("op_id"))
        agg = ops.setdefault(key, {"op": e.get("op"),
                                   "op_id": e.get("op_id"),
                                   "wall_ns": 0, "rows": 0, "batches": 0,
                                   "bytes": 0})
        if kind == "op_close":
            agg["wall_ns"] += e.get("wall_ns") or 0
            agg["rows"] += e.get("rows") or 0
            agg["batches"] += e.get("batches") or 0
        else:
            agg["bytes"] += e.get("bytes") or 0

    rows = sorted(ops.values(), key=lambda r: -r["wall_ns"])
    total_ns = max((r["wall_ns"] for r in rows), default=0)
    top_ops = []
    for r in rows[:top]:
        row = dict(r)
        row["pct_root"] = round(100.0 * r["wall_ns"] / total_ns, 1) \
            if total_ns else 0.0
        top_ops.append(row)

    def count(kind) -> int:
        return sum(1 for e in events if e.get("kind") == kind)

    def total(kind, field) -> int:
        return sum(e.get(field) or 0 for e in events
                   if e.get("kind") == kind)

    def by(kind, field) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for e in events:
            if e.get("kind") == kind:
                k = e.get(field, "?") or "?"
                out[k] = out.get(k, 0) + 1
        return out

    fused = [e for e in events if e.get("kind") == "stage_fused"]
    compiles = [e for e in events if e.get("kind") == "program_compile"]
    storms = [e for e in events if e.get("kind") == "recompile_storm"]
    dstats = [e for e in events if e.get("kind") == "dispatch_stats"]
    writes = [e for e in events if e.get("kind") == "shuffle_write"]
    tiers = [e for e in events if e.get("kind") == "pallas_tier"]
    gstats = [e for e in events if e.get("kind") == "gather_stats"]
    ups = [e for e in events if e.get("kind") == "upload"]
    xstats = [e for e in events if e.get("kind") == "exchange_stats"]
    ici = [e for e in events if e.get("kind") == "ici_exchange"]
    ici_ok = [e for e in ici if not e.get("fallback")]
    escan = [e for e in events if e.get("kind") == "encoded_scan"]
    emat = [e for e in events if e.get("kind") == "encoded_materialize"]
    replans = [e for e in events if e.get("kind") == "adaptive_replan"]
    demotes = [e for e in events if e.get("kind") == "adaptive_demote"]
    waits = [e.get("wait_ms") or 0 for e in events
             if e.get("kind") == "query_admitted"]
    qphases = [e for e in events if e.get("kind") == "query_phases"]
    phase_ns: Dict[str, int] = {}
    for e in qphases:
        for p, v in (e.get("phases") or {}).items():
            phase_ns[p] = phase_ns.get(p, 0) + (v or 0)

    summary: Dict[str, Any] = {
        "events": len(events),
        # wall-clock phase attribution roll-up (ISSUE 17): one
        # query_phases record per governed query, each a closed ledger
        # (sum(phases) == wall_ns) — summed here so a whole log answers
        # "where did the wall-clock go" in one table. Zero-tolerant:
        # pre-phase logs report zeros and print nothing.
        "phases": {
            "queries": len(qphases),
            "wall_ns": sum(e.get("wall_ns") or 0 for e in qphases),
            "by_phase": phase_ns},
        "queries": sorted({e.get("query") for e in events
                           if e.get("query") is not None}),
        "completed": count("query_end"),
        "top_ops": top_ops,
        "operators": len(rows),
        "spills": {"count": count("spill"),
                   "bytes": total("spill", "bytes")},
        "oom_retries": count("oom_retry"),
        "semaphore_wait_ns": total("semaphore_acquire", "wait_ns"),
        "pipeline": {"stages": count("pipeline_wait"),
                     "consumer_wait_ns": total("pipeline_wait",
                                               "wait_ns"),
                     "producer_full_ns": total("pipeline_full",
                                               "full_ns")},
        "exchange_bytes": total("exchange", "bytes"),
        "shuffle_writes": {
            "maps": len(writes),
            "bytes": total("shuffle_write", "bytes"),
            "frames": total("shuffle_write", "frames"),
            "device_partitioned": sum(1 for e in writes
                                      if e.get("lane") == "device"),
            "pack_ns": total("shuffle_write", "pack_ns"),
            "serialize_ns": total("shuffle_write", "serialize_ns"),
            "io_ns": total("shuffle_write", "io_ns")},
        # ICI shuffle roll-up (ISSUE 16): device-resident all-to-all
        # exchange rounds — bytes that never touched the host, the
        # negotiated slot caps and grid fill (the sizing methodology's
        # feedback signal), and how many streams degraded to the host
        # serialize lane. Zero-tolerant: pre-ICI logs report zeros.
        "ici_shuffle": {
            "rounds": len(ici_ok),
            "batches": sum(e.get("batches") or 0 for e in ici_ok),
            "rows": sum(e.get("rows") or 0 for e in ici_ok),
            "bytes": sum(e.get("bytes") or 0 for e in ici_ok),
            "collective_ns": sum(e.get("collective_ns") or 0
                                 for e in ici_ok),
            "max_slot_cap": max((e.get("slot_cap") or 0
                                 for e in ici_ok), default=0),
            "avg_fill": round(sum(e.get("fill") or 0 for e in ici_ok)
                              / len(ici_ok), 4) if ici_ok else 0.0,
            "fallbacks": sum(1 for e in ici if e.get("fallback"))},
        # encoded-execution roll-up (ISSUE 18): scan batches that kept
        # columns dictionary-encoded, the code/dictionary byte split,
        # the eager-decode bytes the lane avoided building, and where
        # the late materializations happened (a healthy plan decodes
        # only at output-level seams). Zero-tolerant: pre-encoded logs
        # report zeros.
        "encoded": {
            "scan_batches": len(escan),
            "cols_encoded": sum(e.get("cols_encoded") or 0
                                for e in escan),
            "codes_bytes": sum(e.get("codes_bytes") or 0
                               for e in escan),
            "dict_bytes": sum(e.get("dict_bytes") or 0 for e in escan),
            "decoded_bytes_avoided": sum(
                e.get("decoded_bytes_avoided") or 0 for e in escan),
            "materializations": sum(e.get("cols") or 0 for e in emat),
            "materialize_seams": by("encoded_materialize", "seam")},
        "plan_fallbacks": (count("plan_fallback")
                           + count("plan_not_on_tpu")),
        "robustness": {
            "injected_faults": by("fault_inject", "point"),
            "io_retries": count("io_retry"),
            "task_retries": count("task_retry"),
            "integrity_quarantines": count("integrity_fail"),
            "watchdog_trips": (count("pipeline_stuck")
                               + count("spill_writer_dead"))},
        "lifecycle": {
            "cancellations": by("query_cancelled", "phase"),
            "breaker": {"open": count("breaker_open"),
                        "half_open": count("breaker_half_open"),
                        "close": count("breaker_close")},
            "partition_recomputes": count("partition_recompute")},
        # straggler-shield roll-up (ISSUE 20): stall episodes by the
        # configured action, speculative sub-read races by winner, hang
        # bounds tripped by breaker domain, and dead-peer map-output
        # invalidations. Zero-tolerant: pre-shield logs print nothing.
        "speculation": {
            "stalls": by("query_stalled", "action"),
            "spec_fetches": count("speculative_fetch"),
            "spec_winners": by("speculative_fetch", "winner"),
            "dispatch_timeouts": by("dispatch_timeout", "domain"),
            "outputs_invalidated": count("map_output_invalidated")},
        "workload": {
            "admissions": count("query_admitted"),
            "queued": count("query_queued"),
            "max_wait_ms": max(waits) if waits else 0,
            "sheds": by("query_shed", "reason"),
            "quota_spills": count("quota_spill")},
        # dispatch/compile roll-up (ISSUE 13): what the per-operator
        # program model costs — how many programs compiled, which
        # labels paid the most compile wall-clock, which stages issue
        # the most dispatches per batch (the whole-stage-compilation
        # baseline), and any recompile storms. Logs from builds without
        # the dispatch plane simply report zeros/empty lists.
        "dispatch": _dispatch_rollup(compiles, storms, dstats, top),
        # whole-stage-compilation roll-up (ISSUE 14): fused-stage
        # executions, operators absorbed, and the dispatches saved vs
        # the per-op baseline (one program per absorbed op per input
        # batch is what the fused program replaced). Zero-tolerant:
        # logs from pre-fusion builds report zeros and print nothing.
        "fused_stages": {
            "executions": len(fused),
            "ops_absorbed": sum(e.get("ops") or 0 for e in fused),
            "batches": sum(e.get("batches") or 0 for e in fused),
            "dispatches": sum(e.get("dispatches") or 0 for e in fused),
            "dispatches_saved": sum(
                max((e.get("ops") or 0) * (e.get("batches") or 0)
                    - (e.get("dispatches") or 0), 0) for e in fused),
            "donated_bytes": max((e.get("donated_bytes") or 0
                                  for e in fused), default=0),
            "by_label": sorted({e.get("label") or "?" for e in fused}),
        },
        "pallas_tier": {"decisions": len(tiers),
                        "engaged": sum(1 for e in tiers
                                       if e.get("engaged"))},
        "gathers": {"count": sum(e.get("count") or 0 for e in gstats),
                    "records": len(gstats),
                    "packed": sum(e.get("packed") or 0 for e in gstats),
                    "pallas": sum(e.get("pallas") or 0 for e in gstats),
                    "bytes": sum(e.get("bytes") or 0 for e in gstats)},
        "uploads": {
            "batches": len(ups),
            "packed": sum(1 for e in ups if e.get("lane") == "packed"),
            "per_buffer": sum(1 for e in ups
                              if e.get("lane") != "packed"),
            "transfers": sum(e.get("transfers") or 0 for e in ups),
            "bytes": sum(e.get("bytes") or 0 for e in ups),
            "pack_ns": sum(e.get("pack_ns") or 0 for e in ups)},
        # runtime-statistics roll-up (ISSUE 11): per-exchange skew +
        # distribution records — worst skew leads, it is the AQE signal.
        # Exchanges may compute skew on different bases (rows vs bytes),
        # so the headline carries the winning exchange's basis alongside.
        "statistics": {
            "exchanges": len(xstats),
            "maps": sum(e.get("maps") or 0 for e in xstats),
            "max_skew_ratio": ((_worst_skew(xstats) or {}).get("skew_ratio")
                               or 0),
            "max_skew_basis": (_worst_skew(xstats) or {}).get("skew_basis"),
            "p95_map_output_bytes": max(
                (e.get("p95_map_output_bytes") or 0 for e in xstats),
                default=0),
            "telemetry_samples": count("telemetry_sample"),
            "per_exchange": [
                {"exec": e.get("exec"), "op_id": e.get("op_id"),
                 "partitions": e.get("partitions"),
                 "maps": e.get("maps"), "rows": e.get("rows"),
                 "bytes": e.get("bytes"),
                 "skew_ratio": e.get("skew_ratio"),
                 "skew_basis": e.get("skew_basis"),
                 "p95_partition_bytes": e.get("p95_partition_bytes"),
                 "p95_map_output_bytes": e.get("p95_map_output_bytes")}
                for e in xstats]},
        # adaptive-execution roll-up (ISSUE 19): what the runtime
        # replanner DID with the measured statistics above — decision
        # counts by kind plus each decision's evidence record
        "adaptive": {
            "replans": len(replans),
            "demotes": len(demotes),
            "skew_splits": sum(1 for e in replans
                               if e.get("decision") == "skew_split"),
            "broadcast_demotes": sum(
                1 for e in demotes
                if e.get("decision") == "broadcast_demote"),
            "single_build_converts": sum(
                1 for e in replans
                if e.get("decision") == "single_build_convert"),
            "partition_coalesces": sum(
                1 for e in replans
                if e.get("decision") == "partition_coalesce"),
            "batch_right_sizes": sum(
                1 for e in replans
                if e.get("decision") == "batch_right_size"),
            "lane_demotions": sum(1 for e in demotes
                                  if e.get("decision") == "lane"),
            "decisions": [
                {k: e.get(k) for k in
                 ("kind", "exec", "op_id", "decision", "reason",
                  "partition", "bytes", "measured_bytes", "threshold",
                  "median_bytes", "subs", "max_sub_bytes", "basis",
                  "reads", "target_bytes", "prev_target", "new_target")
                 if e.get(k) is not None}
                for e in replans + demotes]},
    }
    return summary


def build_report(events: List[Dict[str, Any]], top: int = 10,
                 query: Optional[int] = None) -> str:
    """Text renderer over build_summary — same data, human form."""
    s = build_summary(events, top=top, query=query)
    lines: List[str] = []
    lines.append(f"event log: {s['events']} events, "
                 f"{len(s['queries'])} queries "
                 f"({s['completed']} completed)")

    rows = s["top_ops"]
    if rows:
        lines.append("")
        lines.append(f"top {min(top, s['operators'])} operators by "
                     "inclusive wall time:")
        hdr = (f"{'#':>3} {'operator':<28} {'id':>4} {'time':>10} "
               f"{'%root':>6} {'rows':>12} {'batches':>8} {'bytes':>10}")
        lines.append(hdr)
        lines.append("-" * len(hdr))
        for i, r in enumerate(rows, 1):
            lines.append(
                f"{i:>3} {r['op']:<28} "
                f"{r['op_id'] if r['op_id'] is not None else '-':>4} "
                f"{_fmt_ns(r['wall_ns']):>10} {r['pct_root']:>5.1f}% "
                f"{r['rows']:>12} {r['batches']:>8} "
                f"{_fmt_bytes(r['bytes']):>10}")

    # phase attribution (ISSUE 17): the summed closed ledgers — every
    # governed query's wall partitioned, shares of the summed wall
    ph = s["phases"]
    if ph["queries"]:
        lines.append("")
        lines.append(f"wall-clock phases ({ph['queries']} governed "
                     f"quer{'y' if ph['queries'] == 1 else 'ies'}, "
                     f"{_fmt_ns(ph['wall_ns'])} total):")
        wall = ph["wall_ns"] or 1
        for p, v in sorted(ph["by_phase"].items(),
                           key=lambda kv: -kv[1]):
            if v:
                lines.append(f"    {p:<20} {_fmt_ns(v):>10} "
                             f"{100.0 * v / wall:>5.1f}%")

    extras = []
    if s["spills"]["count"]:
        extras.append(f"spills: {s['spills']['count']} "
                      f"({_fmt_bytes(s['spills']['bytes'])})")
    if s["oom_retries"]:
        extras.append(f"oom retries: {s['oom_retries']}")
    if s["semaphore_wait_ns"]:
        extras.append(f"semaphore wait: "
                      f"{_fmt_ns(s['semaphore_wait_ns'])}")
    pipe = s["pipeline"]
    if pipe["stages"]:
        extras.append(
            f"pipeline stages: {pipe['stages']} (consumer stalled "
            f"{_fmt_ns(pipe['consumer_wait_ns'])} on empty, producer "
            f"stalled {_fmt_ns(pipe['producer_full_ns'])} on full)")
    if s["exchange_bytes"]:
        extras.append(f"exchange bytes: "
                      f"{_fmt_bytes(s['exchange_bytes'])}")
    # shuffle-write roll-up (ISSUE 9): write time split pack (device
    # partition + packed D2H) / serialize / file IO, byte and frame
    # totals, and how many maps rode the device-partition lane
    sw = s["shuffle_writes"]
    if sw["maps"]:
        extras.append(
            f"shuffle writes: {sw['maps']} maps "
            f"({_fmt_bytes(sw['bytes'])} in {sw['frames']} frames; "
            f"{sw['device_partitioned']} device-partitioned; pack "
            f"{_fmt_ns(sw['pack_ns'])}, serialize "
            f"{_fmt_ns(sw['serialize_ns'])}, io "
            f"{_fmt_ns(sw['io_ns'])})")
    # ICI shuffle roll-up (ISSUE 16): the host-serialize collapse is
    # the optimization, so a pod round reads this line right under the
    # shuffle-write (host lane) one
    ic = s["ici_shuffle"]
    if ic["rounds"] or ic["fallbacks"]:
        extras.append(
            f"ici shuffle: {ic['rounds']} collective round(s) "
            f"({ic['batches']} map batches, {ic['rows']} rows, "
            f"{_fmt_bytes(ic['bytes'])} device-to-device in "
            f"{_fmt_ns(ic['collective_ns'])}; slot cap "
            f"{ic['max_slot_cap']}, fill {ic['avg_fill']:.2f}; "
            f"{ic['fallbacks']} host-lane fallback(s))")
    # encoded-execution roll-up (ISSUE 18): the decode-avoided bytes
    # are the optimization, so a round reads this line next to the
    # uploads one
    en = s["encoded"]
    if en["scan_batches"] or en["materializations"]:
        seams = ", ".join(f"{k}:{n}" for k, n in
                          sorted(en["materialize_seams"].items()))
        extras.append(
            f"encoded columns: {en['cols_encoded']} across "
            f"{en['scan_batches']} scan batch(es) "
            f"({_fmt_bytes(en['codes_bytes'])} codes + "
            f"{_fmt_bytes(en['dict_bytes'])} dictionaries, "
            f"{_fmt_bytes(en['decoded_bytes_avoided'])} eager decode "
            f"avoided; {en['materializations']} late "
            f"materialization(s){' at ' + seams if seams else ''})")
    if s["plan_fallbacks"]:
        extras.append(f"plan fallback/why-not records: "
                      f"{s['plan_fallbacks']}")
    # robustness roll-up (docs/robustness.md): how much chaos the run
    # absorbed, and at which recovery layer
    rob = s["robustness"]
    if rob["injected_faults"]:
        n_inject = sum(rob["injected_faults"].values())
        detail = ", ".join(f"{p}:{n}" for p, n
                           in sorted(rob["injected_faults"].items()))
        extras.append(f"injected faults: {n_inject} ({detail})")
    if rob["io_retries"]:
        extras.append(f"io retries: {rob['io_retries']}")
    if rob["task_retries"]:
        extras.append(f"task re-executions: {rob['task_retries']}")
    # lifecycle-governor roll-up (ISSUE 6): cancellations by phase,
    # breaker transitions, and which recovery lane paid for failures
    lc = s["lifecycle"]
    if lc["cancellations"]:
        n_cancel = sum(lc["cancellations"].values())
        detail = ", ".join(f"{p}:{n}" for p, n
                           in sorted(lc["cancellations"].items()))
        extras.append(f"query cancellations: {n_cancel} ({detail})")
    br = lc["breaker"]
    if br["open"] or br["half_open"] or br["close"]:
        extras.append(f"breaker trips: {br['open']} open, "
                      f"{br['half_open']} half-open, "
                      f"{br['close']} close")
    # only when the partition lane actually engaged — the whole-plan
    # count already prints as "task re-executions" above, and repeating
    # it alone would state the same figure twice
    if lc["partition_recomputes"]:
        extras.append(f"recovery lanes: {lc['partition_recomputes']} "
                      f"partition-granular recompute(s), "
                      f"{rob['task_retries']} whole-plan "
                      "re-execution(s)")
    # straggler-shield roll-up (ISSUE 20): reads right under the
    # recovery lanes it feeds — a stalled/straggling run shows WHERE
    # the shield intervened next to what the retry lanes then paid
    sp = s["speculation"]
    if sp["stalls"]:
        n_stall = sum(sp["stalls"].values())
        detail = ", ".join(f"{a}:{n}" for a, n
                           in sorted(sp["stalls"].items()))
        extras.append(f"query stalls: {n_stall} ({detail})")
    if sp["spec_fetches"]:
        w = sp["spec_winners"]
        extras.append(
            f"speculative sub-reads: {sp['spec_fetches']} "
            f"({w.get('spec', 0)} spec won, "
            f"{w.get('primary', 0)} primary won)")
    if sp["dispatch_timeouts"]:
        n_to = sum(sp["dispatch_timeouts"].values())
        detail = ", ".join(f"{d}:{n}" for d, n
                           in sorted(sp["dispatch_timeouts"].items()))
        extras.append(f"dispatch hang bounds tripped: {n_to} ({detail})")
    if sp["outputs_invalidated"]:
        extras.append(f"dead-peer map outputs invalidated: "
                      f"{sp['outputs_invalidated']}")
    # workload-governor roll-up (ISSUE 7): admission flow, sheds by
    # reason, and quota-triggered self-spills
    wl = s["workload"]
    if wl["admissions"] or wl["queued"] or wl["sheds"]:
        extras.append(
            f"workload admissions: {wl['admissions']} "
            f"({wl['queued']} queued, max wait {wl['max_wait_ms']}ms)")
    if wl["sheds"]:
        n_shed = sum(wl["sheds"].values())
        detail = ", ".join(f"{r}:{n}" for r, n
                           in sorted(wl["sheds"].items()))
        extras.append(f"queries shed: {n_shed} ({detail})")
    if wl["quota_spills"]:
        extras.append(f"quota spills: {wl['quota_spills']} "
                      f"(over-share queries spilled their own entries)")
    if rob["integrity_quarantines"]:
        extras.append(f"integrity quarantines: "
                      f"{rob['integrity_quarantines']}")
    if rob["watchdog_trips"]:
        extras.append(f"watchdog trips: {rob['watchdog_trips']}")
    # dispatch/compile roll-up (ISSUE 13): compile spend by program
    # label and the per-stage dispatch rate the whole-stage-compilation
    # work must collapse; absent entirely for pre-dispatch-plane logs
    dp = s["dispatch"]
    if dp["programs_compiled"]:
        extras.append(
            f"program compiles: {dp['programs_compiled']} "
            f"(compile {_fmt_ns(dp['compile_ns'])}, trace "
            f"{_fmt_ns(dp['trace_ns'])})")
        worst = dp["top_by_compile_ns"][:3]
        if worst:
            detail = ", ".join(
                f"{r['label']}:{_fmt_ns(r['compile_ns'])}"
                for r in worst)
            extras.append(f"  top compile cost: {detail}")
    rate = [r for r in dp["top_by_dispatches_per_batch"]
            if r["dispatches_per_batch"]][:3]
    if rate:
        detail = ", ".join(
            f"{r['op']}#{r['op_id']}:{r['dispatches_per_batch']}"
            for r in rate)
        extras.append(f"dispatches/batch (top stages): {detail}")
    if dp["storms"]:
        detail = ", ".join(
            f"{r['label']}({r['traces_in_window']} traces/"
            f"{r['window_ms']}ms)" for r in dp["storms"][:3])
        extras.append(f"RECOMPILE STORMS: {len(dp['storms'])} "
                      f"({detail})")
    # fused-stage roll-up (ISSUE 14): how much per-operator dispatch
    # overhead whole-stage compilation collapsed; absent on pre-fusion
    # logs
    fs = s["fused_stages"]
    if fs["executions"]:
        extras.append(
            f"fused stages: {fs['executions']} execution(s) "
            f"({fs['ops_absorbed']} ops absorbed, {fs['dispatches']} "
            f"dispatches over {fs['batches']} batches — "
            f"~{fs['dispatches_saved']} saved vs per-op; donated "
            f"state {_fmt_bytes(fs['donated_bytes'])})")
    pt = s["pallas_tier"]
    if pt["decisions"]:
        extras.append(f"pallas tier decisions: {pt['decisions']} "
                      f"({pt['engaged']} engaged)")
    # gather-engine roll-up (ISSUE 8): materializing row gathers per
    # wired operator — the count drop IS the optimization, so a bench
    # round reads it next to the pipeline/workload lines
    g = s["gathers"]
    if g["records"]:
        extras.append(
            f"gathers: {g['count']} ({g['packed']} packed rows, "
            f"{g['pallas']} via the Pallas DMA kernel, "
            f"~{_fmt_bytes(g['bytes'])} moved)")
    # upload-engine roll-up (ISSUE 10): host->device ingest — the
    # transfer-count drop (one per batch vs one per buffer) is the
    # optimization, so a round reads it next to the gather line
    u = s["uploads"]
    if u["batches"]:
        extras.append(
            f"uploads: {u['batches']} batches ({u['packed']} packed, "
            f"{u['per_buffer']} per-buffer; {u['transfers']} h2d "
            f"transfers, {_fmt_bytes(u['bytes'])}, pack "
            f"{_fmt_ns(u['pack_ns'])})")
    # runtime-statistics roll-up (ISSUE 11): the exchange skew line an
    # AQE round (ROADMAP 4) reads first
    st = s["statistics"]
    if st["exchanges"]:
        basis = f" (by {st['max_skew_basis']})" if st.get("max_skew_basis") else ""
        extras.append(
            f"statistics: {st['exchanges']} exchange(s), "
            f"{st['maps']} map outputs; max partition skew ratio "
            f"{st['max_skew_ratio']:.2f}{basis}, p95 map output "
            f"{_fmt_bytes(st['p95_map_output_bytes'])}")
    if st["telemetry_samples"]:
        extras.append(f"telemetry samples: {st['telemetry_samples']}")
    # adaptive-execution roll-up (ISSUE 19): what the runtime replanner
    # did with those measured statistics — reads right under the skew
    # line it acted on
    ad = s["adaptive"]
    if ad["replans"] or ad["demotes"]:
        extras.append(
            f"adaptive decisions: {ad['skew_splits']} skew split(s), "
            f"{ad['broadcast_demotes']} broadcast demotion(s), "
            f"{ad['single_build_converts']} single-build conversion(s), "
            f"{ad['partition_coalesces']} coalesce(s), "
            f"{ad['batch_right_sizes']} batch right-sizing(s)"
            + (f", {ad['lane_demotions']} lane stand-down(s)"
               if ad["lane_demotions"] else ""))
    if extras:
        lines.append("")
        lines.extend(extras)
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("log", help="events-*.jsonl file (obs/events.py); "
                               "a rotated set is read in order")
    ap.add_argument("--top", type=int, default=10,
                    help="operators to show (default 10)")
    ap.add_argument("--query", type=int, default=None,
                    help="restrict to one query id")
    ap.add_argument("--format", choices=("text", "json"),
                    default="text",
                    help="text table (default) or the machine-readable "
                         "summary JSON")
    args = ap.parse_args(argv)
    events = read_event_files(args.log)
    if args.format == "json":
        print(json.dumps(build_summary(events, top=args.top,
                                       query=args.query), indent=2))
    else:
        print(build_report(events, top=args.top, query=args.query))
    return 0


if __name__ == "__main__":
    sys.exit(main())
