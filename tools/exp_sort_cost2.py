"""v2 sort-cost microbench: all inputs generated ON DEVICE (the v1
script's 200 MB of host-side constant uploads never finished over the
tunnel), forced-checksum timing, progress printed per step."""

import sys
import time

sys.path.insert(0, "/root/repo")

import numpy as np
import jax
import jax.numpy as jnp

N = 1 << 21
print("building device inputs", flush=True)
iota = jnp.arange(N, dtype=jnp.int32)
# cheap on-device pseudo-random u32s (LCG mix of iota)
keys = (iota.astype(jnp.uint32) * jnp.uint32(2654435761)
        + jnp.uint32(12345)) ^ (iota.astype(jnp.uint32) >> 7)
mat8 = (keys[:, None] * (jnp.arange(8, dtype=jnp.uint32) + 1)[None, :])
perm = jax.lax.sort((keys, iota), num_keys=1)[1]
jax.block_until_ready(mat8)
fmat = (keys.astype(jnp.float64) * 1e-3)[:, None] * jnp.ones(
    (1, 2), jnp.float64)
print("inputs ready", flush=True)


def timed(name, fn, iters=6):
    out = fn(jnp.uint32(0))
    float(np.asarray(out))  # force compile + first run
    t0 = time.perf_counter()
    chk = jnp.uint32(0)
    for _ in range(iters):
        chk = fn(chk)
    float(np.asarray(chk))
    dt = (time.perf_counter() - t0) / iters * 1e3
    print(f"{name:42s} {dt:8.1f} ms", flush=True)


def sort_l(lanes):
    @jax.jit
    def f(salt):
        ops = [keys ^ salt] + [keys] * (lanes - 1) + [iota]
        out = jax.lax.sort(tuple(ops), num_keys=lanes)
        return out[-1][0].astype(jnp.uint32)
    return f


for L in (1, 2, 4, 6):
    timed(f"sort {L} u32 keys + iota key", sort_l(L))


@jax.jit
def sort_payload8(salt):
    ops = [keys ^ salt, iota] + [mat8[:, j] for j in range(8)]
    out = jax.lax.sort(tuple(ops), num_keys=2)
    return out[2][0].astype(jnp.uint32)


timed("sort 1 key + iota + 8 u32 payload", sort_payload8)


@jax.jit
def sort_payload8_f2(salt):
    ops = [keys ^ salt, iota] + [mat8[:, j] for j in range(8)] \
        + [fmat[:, 0], fmat[:, 1]]
    out = jax.lax.sort(tuple(ops), num_keys=2)
    return out[2][0].astype(jnp.uint32)


timed("sort 1key+iota+8u32+2f64 payload", sort_payload8_f2)


@jax.jit
def gather8(salt):
    g = mat8[perm]
    return g[0, 0] + salt


timed("row gather (N,8) u32 matrix", gather8)


@jax.jit
def fused_flag_sort(salt):
    flag = (keys ^ salt) >> jnp.uint32(31)
    word = (flag << jnp.uint32(31)) | iota.astype(jnp.uint32)
    out = jax.lax.sort((word,), num_keys=1)
    return out[0][0]


timed("compaction fused flag|iota 1 lane", fused_flag_sort)


@jax.jit
def two_lane_compaction(salt):
    flag = (keys ^ salt) >> jnp.uint32(31)
    out = jax.lax.sort((flag, iota), num_keys=2)
    return out[1][0].astype(jnp.uint32)


timed("compaction flag + iota 2 lanes", two_lane_compaction)


@jax.jit
def segscan_f64(salt):
    seg_start = (keys ^ salt) < jnp.uint32(1 << 24)

    def comb(a, b):
        av, af = a
        bv, bf = b
        return jnp.where(bf, bv, av + bv), af | bf
    v = fmat[:, 0]
    out, _ = jax.lax.associative_scan(comb, (v, seg_start))
    return out[0].astype(jnp.uint32) + salt


timed("segmented f64 cumsum (assoc scan)", segscan_f64)


@jax.jit
def plain_cumsum(salt):
    return jnp.cumsum(fmat[:, 0])[0].astype(jnp.uint32) + salt


timed("plain f64 cumsum", plain_cumsum)


@jax.jit
def segsum_scatter(salt):
    seg = (keys ^ salt) >> jnp.uint32(13)  # ~256K segments
    out = jax.ops.segment_sum(fmat[:, 0], seg.astype(jnp.int32),
                              num_segments=1 << 19)
    return out[0].astype(jnp.uint32) + salt


timed("segment_sum scatter f64 -> 512K", segsum_scatter)
