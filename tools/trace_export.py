"""Convert a spark_rapids_tpu event log (JSONL, obs/events.py) into
Chrome trace format JSON — loadable in Perfetto (ui.perfetto.dev) or
chrome://tracing, so pipeline overlap, compile stalls and spill storms
are VISIBLE as a timeline instead of inferred from roll-up totals
(ISSUE 13 tentpole part 2).

Usage:
    python tools/trace_export.py EVENTS.jsonl [-o trace.json]
                                 [--query QID]

Given any member of a rotated log set (eventLog.maxBytes), the whole
set is read in rotation order. Stdlib only.

Mapping
-------
* One timeline TRACK per emitting thread — the `thread` field every
  event record carries (ISSUE 13 satellite): the consumer
  (MainThread), each `pipeline-*` producer, the `spill-writer`, the
  `multifile-read`/`shuffle-*` pool workers. Records from builds
  predating the field land on one `<unknown>` track.
* Operator executions become complete ("X") spans synthesized from
  `op_close` (ts - wall_ns .. ts). Wall time is INCLUSIVE of child
  time (the pull model), so parent/child operator spans nest exactly
  like the reference's NVTX ranges. With a DEBUG-level log, `op_batch`
  records additionally become per-batch spans one nesting level in.
* Pipeline stage stalls (`pipeline_wait` / `pipeline_full`) become
  spans on their emitting thread sized by the stall total.
* Point events — spills, faults, IO/OOM/task retries, integrity
  quarantines, program compiles, recompile storms, breaker/lifecycle
  transitions — become instant ("i") events on their thread's track.
* `telemetry_sample` records become counter ("C") tracks (HBM by
  tier, budget use, admission queue depth) so resource pressure reads
  directly under the spans that caused it.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, Iterable, List, Optional

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from profile_report import read_event_files  # noqa: E402

#: event kinds rendered as instants, with the fields worth carrying
#: into the args pane (everything else the record has rides along too)
INSTANT_KINDS = (
    "spill", "spill_error", "oom_retry", "io_retry", "task_retry",
    "fault_inject", "integrity_fail", "program_compile",
    "recompile_storm", "pipeline_stuck", "spill_writer_dead",
    "query_cancelled", "query_shed", "breaker_open",
    "breaker_half_open", "breaker_close", "partition_recompute",
    "quota_spill", "query_queued", "query_admitted", "peer_dead",
    "pallas_tier", "shuffle_write", "upload", "exchange_stats",
    "gather_stats", "dispatch_stats",
)

#: telemetry series promoted to counter tracks (a readable subset —
#: the full sample still lands in the args of its instant)
COUNTER_SERIES = (
    "hbm.device_bytes", "hbm.host_bytes", "budget.used_bytes",
    "workload.queue_depth", "sem.wait_ns", "queries.active",
)

PID = 1


def _us(ts_ns: int) -> float:
    return ts_ns / 1_000.0


class _Tids:
    """Stable tid per thread name; insertion order = first appearance,
    with MainThread pinned to tid 1 so the consumer track sorts first."""

    def __init__(self):
        self._by_name: Dict[str, int] = {}

    def get(self, name: Optional[str]) -> int:
        name = name or "<unknown>"
        if name == "MainThread":
            self._by_name.setdefault(name, 1)
        if name not in self._by_name:
            taken = set(self._by_name.values())
            n = 2
            while n in taken:
                n += 1
            self._by_name[name] = n
        return self._by_name[name]

    def metadata(self) -> List[Dict[str, Any]]:
        out = [{"ph": "M", "pid": PID, "tid": 0,
                "name": "process_name",
                "args": {"name": "spark_rapids_tpu"}}]
        for name, tid in sorted(self._by_name.items(),
                                key=lambda kv: kv[1]):
            out.append({"ph": "M", "pid": PID, "tid": tid,
                        "name": "thread_name", "args": {"name": name}})
        return out


def _span_args(e: Dict[str, Any]) -> Dict[str, Any]:
    return {k: v for k, v in e.items()
            if k not in ("kind", "ts_ns", "thread")}


def build_trace(events: List[Dict[str, Any]],
                query: Optional[int] = None) -> Dict[str, Any]:
    """Chrome trace JSON object ({"traceEvents": [...]}) from parsed
    event records. Tolerates logs from builds without the `thread`
    field (one merged track) and without the dispatch plane (no
    compile instants — everything else still renders)."""
    if query is not None:
        events = [e for e in events if e.get("query") == query]
    tids = _Tids()
    out: List[Dict[str, Any]] = []
    for e in events:
        kind = e.get("kind")
        ts = e.get("ts_ns")
        if kind is None or ts is None:
            continue
        tid = tids.get(e.get("thread"))
        if kind == "op_close":
            wall = int(e.get("wall_ns") or 0)
            out.append({
                "ph": "X", "pid": PID, "tid": tid,
                "name": str(e.get("op")),
                "ts": _us(ts - wall), "dur": wall / 1_000.0,
                "cat": "operator", "args": _span_args(e)})
        elif kind == "op_batch":
            wall = int(e.get("wall_ns") or 0)
            out.append({
                "ph": "X", "pid": PID, "tid": tid,
                "name": f"{e.get('op')}#batch",
                "ts": _us(ts - wall), "dur": wall / 1_000.0,
                "cat": "batch", "args": _span_args(e)})
        elif kind in ("pipeline_wait", "pipeline_full"):
            stall = int(e.get("wait_ns") or e.get("full_ns") or 0)
            out.append({
                "ph": "X", "pid": PID, "tid": tid,
                "name": f"{kind}:{e.get('stage')}",
                "ts": _us(ts - stall), "dur": stall / 1_000.0,
                "cat": "stall", "args": _span_args(e)})
        elif kind == "telemetry_sample":
            for series in COUNTER_SERIES:
                if series in e:
                    out.append({
                        "ph": "C", "pid": PID, "tid": 0,
                        "name": series, "ts": _us(ts),
                        "args": {"value": e[series]}})
        elif kind in INSTANT_KINDS:
            out.append({
                "ph": "i", "pid": PID, "tid": tid, "s": "t",
                "name": kind, "ts": _us(ts), "cat": "event",
                "args": _span_args(e)})
        elif kind in ("query_start", "query_end"):
            out.append({
                "ph": "i", "pid": PID, "tid": tid, "s": "p",
                "name": f"{kind}:{e.get('query')}", "ts": _us(ts),
                "cat": "query", "args": _span_args(e)})
    return {"traceEvents": tids.metadata() + out,
            "displayTimeUnit": "ms"}


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("log", help="events-*.jsonl file (obs/events.py); "
                               "a rotated set is read in order")
    ap.add_argument("-o", "--out", default=None,
                    help="output path (default: <log>.trace.json)")
    ap.add_argument("--query", type=int, default=None,
                    help="restrict to one query id")
    args = ap.parse_args(argv)
    events = read_event_files(args.log)
    trace = build_trace(events, query=args.query)
    out_path = args.out or (args.log + ".trace.json")
    with open(out_path, "w") as f:
        json.dump(trace, f)
    n_tracks = sum(1 for t in trace["traceEvents"]
                   if t.get("ph") == "M" and t["name"] == "thread_name")
    print(f"{out_path}: {len(trace['traceEvents'])} trace events, "
          f"{n_tracks} thread tracks — load in ui.perfetto.dev")
    return 0


if __name__ == "__main__":
    sys.exit(main())
