"""Regenerate docs from the live registries (the reference generates
docs/configs.md from RapidsConf.help and docs/supported_ops.md — 20k
lines — from the TypeChecks tables; SURVEY §2.11).

Usage: python tools/gen_docs.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def gen_configs() -> str:
    from spark_rapids_tpu.config import generate_docs
    return generate_docs()


def gen_supported_ops() -> str:
    """docs/supported_ops.md from the expression/exec rule tables (the
    reference's TypeChecks-generated support matrix)."""
    from spark_rapids_tpu.plan.overrides import (aggregate_window_rules,
        expression_rules)
    lines = [
        "# spark_rapids_tpu supported operations",
        "",
        "Generated from the rule tables in `spark_rapids_tpu/plan/"
        "overrides.py` (the reference generates docs/supported_ops.md "
        "from its TypeChecks tables the same way).",
        "",
        "## Expressions",
        "",
        "| Expression | Description | Input types | Output types |",
        "|---|---|---|---|",
    ]
    rules = dict(expression_rules())
    rules.update(aggregate_window_rules())
    for cls in sorted(rules, key=lambda c: c.__name__):
        r = rules[cls]
        lines.append(
            f"| `{cls.__name__}` | {r.desc} | "
            f"{', '.join(sorted(r.input_sig.tags))} "
            f"| {', '.join(sorted(r.output_sig.tags))} |")

    lines += [
        "",
        "## Execs",
        "",
        "| Exec | Converted from | Notes |",
        "|---|---|---|",
    ]
    execs = [
        ("ProjectExec", "LogicalProject",
         "tiered projection with CSE; host fallback tier"),
        ("FilterExec", "LogicalFilter",
         "predicate pushdown into scans; host fallback tier"),
        ("RangeExec", "LogicalRange", ""),
        ("ExpandExec", "LogicalExpand", ""),
        ("UnionExec", "LogicalUnion", ""),
        ("AggregateExec", "LogicalAggregate",
         "partial/final modes; masked-bucket fast tier + exact fallback"),
        ("SortExec", "LogicalSort", "out-of-core spill-backed run merge"),
        ("TopNExec", "LogicalSort+limit", ""),
        ("GlobalLimitExec", "LogicalLimit", "offset supported"),
        ("WindowExec", "LogicalWindow",
         "running/unbounded/bounded row frames, partition-aware batching"),
        ("GenerateExec", "LogicalGenerate",
         "explode/posexplode, outer variants"),
        ("HashJoinExec", "LogicalJoin",
         "all join types; broadcast build side"),
        ("NestedLoopJoinExec", "LogicalJoin (keyless)", "cross + filtered"),
        ("ShuffleExchangeExec", "planner-inserted",
         "ICI all-to-all over the device mesh"),
        ("HostShuffleExchangeExec", "planner-inserted",
         "MULTITHREADED host shuffle: LZ4 blocks, data+index files"),
        ("BroadcastExchangeExec", "planner-inserted",
         "device-resident replicated build side"),
        ("ShuffledHashJoinExec", "planner-inserted",
         "per-partition join over exchanged sides"),
        ("SampleExec", "LogicalSample", "Bernoulli sampling, threefry RNG"),
        ("PartitionWiseSortExec", "planner-inserted",
         "global sort via range exchange + per-partition sort"),
        ("SourceScanExec", "LogicalScan",
         "streaming file-source scan; pipelined decode + upload"),
        ("CoalesceBatchesExec", "transition pass",
         "target-bucket concat; pipelined input"),
        ("ColumnarToRowExec / RowToColumnarExec", "transition pass",
         "host row-engine fallback boundary"),
        ("HostProjectExec / HostFilterExec", "CPU fallback",
         "host row interpreter for expressions without device kernels"),
    ]
    for name, src, note in execs:
        lines.append(f"| `{name}` | {src} | {note} |")
    lines.append("")
    return "\n".join(lines)


def main():
    root = os.path.join(os.path.dirname(__file__), "..", "docs")
    os.makedirs(root, exist_ok=True)
    with open(os.path.join(root, "configs.md"), "w") as f:
        f.write(gen_configs())
    with open(os.path.join(root, "supported_ops.md"), "w") as f:
        f.write(gen_supported_ops())
    print("wrote docs/configs.md and docs/supported_ops.md")


if __name__ == "__main__":
    main()
